"""Property-based torus search tests: correctness AND completeness
against a brute-force oracle over randomized fleets.

The torus search is the scheduler's hardest pure logic (VERDICT round
1 called out the missing wrap-around/odd-shape property coverage);
hypothesis drives it through shapes unit tests won't think of.
"""

import pytest

pytest.importorskip(
    "hypothesis", reason="property tests need the hypothesis package"
)
from hypothesis import given, settings, strategies as st  # noqa: E402

from dcos_commons_tpu.offer.inventory import (
    ResourceSnapshot,
    TpuHost,
)
from dcos_commons_tpu.offer.outcome import EvaluationOutcome
from dcos_commons_tpu.offer.torus import find_subslice


def make_grid(width, height, blocked, chip_block=(2, 2), wrap=""):
    """Snapshots for a width x height host grid; ``blocked`` hosts are
    ineligible (their chips reserved)."""
    snaps = []
    for y in range(height):
        for x in range(width):
            attrs = {}
            if wrap:
                attrs = {
                    "ici_wrap": wrap,
                    "ring_x": str(width),
                    "ring_y": str(height),
                }
            host = TpuHost(
                host_id=f"h{x}-{y}",
                slice_id="prop-slice",
                generation="v5e",
                grid=(x, y),
                chip_block=chip_block,
                cpus=8.0,
                memory_mb=16384,
                attributes=attrs,
            )
            free = set() if (x, y) in blocked else set(host.chip_ids())
            snaps.append(ResourceSnapshot(
                host, host.cpus, host.memory_mb, host.disk_mb, free, set()
            ))
    return snaps


def all_ok(_snap):
    return EvaluationOutcome.ok("prop")


def brute_force_exists(width, height, blocked, need_x, need_y, wrap_x,
                       wrap_y):
    """Oracle: does ANY (possibly wrapped) axis-aligned rect of
    need_x x need_y unblocked hosts exist?"""
    anchors_x = range(width) if wrap_x and need_x < width else range(
        width - need_x + 1
    )
    anchors_y = range(height) if wrap_y and need_y < height else range(
        height - need_y + 1
    )
    for ay in anchors_y:
        for ax in anchors_x:
            cells = [
                ((ax + dx) % width, (ay + dy) % height)
                for dy in range(need_y)
                for dx in range(need_x)
            ]
            if len(set(cells)) == len(cells) and not any(
                c in blocked for c in cells
            ):
                return True
    return False


grids = st.tuples(
    st.integers(min_value=1, max_value=4),   # width
    st.integers(min_value=1, max_value=4),   # height
)


@settings(max_examples=60, deadline=None)
@given(
    grid=grids,
    need=grids,
    blocked_seed=st.integers(min_value=0, max_value=2 ** 16),
    block_fraction=st.floats(min_value=0.0, max_value=0.8),
    wrap=st.sampled_from(["", "x", "y", "both"]),
)
def test_search_matches_brute_force(grid, need, blocked_seed,
                                    block_fraction, wrap):
    import random

    width, height = grid
    need_hx, need_hy = need
    if need_hx > width or need_hy > height:
        return  # trivially unplaceable; covered by explicit tests
    rng = random.Random(blocked_seed)
    blocked = {
        (x, y)
        for y in range(height)
        for x in range(width)
        if rng.random() < block_fraction
    }
    bw, bh = 2, 2
    topology = (need_hx * bw, need_hy * bh)
    snaps = make_grid(width, height, blocked, (bw, bh), wrap)
    placement = find_subslice(snaps, topology, bw * bh, all_ok)

    wrap_x = wrap in ("x", "both") and need_hx < width
    wrap_y = wrap in ("y", "both") and need_hy < height
    expected = brute_force_exists(
        width, height, blocked, need_hx, need_hy, wrap_x, wrap_y
    )
    found = bool(placement.snapshots)
    assert found == expected, (
        f"search {'missed' if expected else 'invented'} a placement: "
        f"grid={grid} need={need} blocked={sorted(blocked)} wrap={wrap!r}"
    )
    if found:
        # correct count, unique hosts, none blocked
        assert len(placement.snapshots) == need_hx * need_hy
        cells = [s.host.grid for s in placement.snapshots]
        assert len(set(cells)) == len(cells)
        assert not any(c in blocked for c in cells)
        # contiguity: cells form one axis-aligned (possibly wrapped)
        # rectangle — successive x deltas are +1 mod ring
        xs = sorted({c[0] for c in cells})
        ys = sorted({c[1] for c in cells})
        if not wrap_x:
            assert xs == list(range(min(xs), min(xs) + need_hx))
        if not wrap_y:
            assert ys == list(range(min(ys), min(ys) + need_hy))


@settings(max_examples=30, deadline=None)
@given(
    grid=grids,
    blocked_seed=st.integers(min_value=0, max_value=2 ** 16),
)
def test_full_grid_always_found_when_clear(grid, blocked_seed):
    width, height = grid
    snaps = make_grid(width, height, set(), (2, 2))
    placement = find_subslice(
        snaps, (width * 2, height * 2), 4, all_ok
    )
    assert len(placement.snapshots) == width * height
