"""Continuous-batching serving engine (the serve data plane's core).

``engine.py`` is the model-agnostic half: a slot-pool admission loop
that admits waiting requests into free KV slots at EVERY decode step
and retires finished rows immediately (per-row EOS / max-token), so a
batch never pads out to its longest row and a new request's time-to-
first-token is one decode tick + its own prefill instead of a whole
preceding generation.  ``pool.py`` is the device half: the jitted
prefill-into-slot / decode-step pair over a persistent static-shape
slot-pool cache (models/decode.py), shared by the single-chip server
and the multi-host gang driver.
"""

from dcos_commons_tpu.serve.engine import (
    SERVESTATS_NAME,
    SlotEngine,
    read_servestats,
)

__all__ = ["SERVESTATS_NAME", "SlotEngine", "read_servestats"]
