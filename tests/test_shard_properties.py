"""Property-based tests for shardcheck's footprint arithmetic.

The footprint model's load-bearing claim (developer-guide §10) is
EXACTNESS: for a leaf every sharded dim divides evenly, the bytes
shardcheck charges each chip times the number of chips equals the
unsharded bytes times the replication factor — i.e. nothing is lost
or double-counted by the per-dim division.  These properties pin that
down over randomly sharded abstract trees on random meshes, the same
hypothesis-importorskip pattern as tests/test_plan_properties.py.
"""

import pytest

pytest.importorskip(
    "hypothesis", reason="property tests need the hypothesis package"
)
from hypothesis import given, settings, strategies as st  # noqa: E402

from dcos_commons_tpu.analysis.shardcheck import (  # noqa: E402
    AbstractLeaf,
    _prod,
    shard_leaf,
)
from dcos_commons_tpu.parallel.mesh import MeshSpec  # noqa: E402

AXES = ("dcn", "dp", "fsdp", "ep", "pp", "sp", "tp")


@st.composite
def mesh_and_leaf(draw, divisible=True):
    """A random MeshSpec plus one abstract leaf whose PartitionSpec
    assigns each mesh axis to at most one dim (the JAX rule).  With
    ``divisible`` every sharded dim is a multiple of its axis product;
    otherwise one sharded dim is bumped off the multiple."""
    sizes = {a: draw(st.integers(1, 4)) for a in AXES}
    mesh = MeshSpec(**sizes)
    rank = draw(st.integers(1, 4))
    dim_axes = [[] for _ in range(rank)]
    for axis in AXES:
        slot = draw(st.integers(-1, rank - 1))
        if slot >= 0:
            dim_axes[slot].append(axis)
    shape = []
    spec = []
    for names in dim_axes:
        q = _prod(sizes[a] for a in names)
        shape.append(q * draw(st.integers(1, 3)))
        spec.append(tuple(names))
    bumped = None
    if not divisible:
        candidates = [
            i for i, names in enumerate(dim_axes)
            if _prod(sizes[a] for a in names) > 1
        ]
        if candidates:
            bumped = draw(st.sampled_from(candidates))
            q = _prod(sizes[a] for a in dim_axes[bumped])
            shape[bumped] += draw(st.integers(1, q - 1))
    leaf = AbstractLeaf(
        path="params/leaf",
        shape=tuple(shape),
        dtype_bytes=draw(st.sampled_from([1, 2, 4])),
        spec=tuple(spec),
        section="params",
    )
    return mesh, leaf, bumped


@settings(max_examples=300, deadline=None)
@given(mesh_and_leaf())
def test_footprint_is_exact_for_divisible_trees(case):
    """sum over chips == unsharded bytes x replication factor, and the
    shard product times the replication factor tiles the mesh."""
    mesh, leaf, _ = case
    report = shard_leaf(leaf, mesh.axes())
    assert not report.problems, report.problems
    assert report.per_chip_bytes * mesh.total \
        == leaf.bytes * report.replication
    assert report.shard_product * report.replication == mesh.total
    # equivalent spelling: the shards of one replica sum to the leaf
    assert report.per_chip_bytes * report.shard_product == leaf.bytes


@settings(max_examples=300, deadline=None)
@given(mesh_and_leaf(divisible=False))
def test_non_divisible_dims_report_and_overcount(case):
    """A dim its axis product does not divide is REPORTED, and the
    padded (ceil) accounting can only overcharge, never undercharge —
    the safe direction for an HBM budget check."""
    mesh, leaf, bumped = case
    report = shard_leaf(leaf, mesh.axes())
    assert report.per_chip_bytes * report.shard_product >= leaf.bytes
    if bumped is None:
        assert not report.problems
        return
    rules = {rule for rule, _, _ in report.problems}
    assert rules == {"shard-divisibility"}, report.problems
    detail = "\n".join(msg for _, _, msg in report.problems)
    assert f"dim {bumped}" in detail


@settings(max_examples=200, deadline=None)
@given(mesh_and_leaf(), st.integers(0, 6))
def test_unknown_axis_vocabulary_contract(case, which):
    """An axis outside BOTH the mesh and the harvested vocabulary is
    flagged; the same axis inside the vocabulary (declared by some
    Mesh(...) elsewhere in the tree, just not laid here) acts as
    size 1 silently."""
    mesh, leaf, _ = case
    dim = which % len(leaf.shape)
    spec = list(leaf.spec)
    spec[dim] = spec[dim] + ("model",)
    poked = AbstractLeaf(
        leaf.path, leaf.shape, leaf.dtype_bytes, tuple(spec),
        leaf.section,
    )
    report = shard_leaf(poked, mesh.axes())
    assert any(rule == "shard-unknown-axis"
               for rule, _, _ in report.problems), report.problems
    allowed = shard_leaf(poked, mesh.axes(), vocab=frozenset({"model"}))
    assert not [p for p in allowed.problems
                if p[0] == "shard-unknown-axis"]
    # unknown axes never change the arithmetic (they shard nothing)
    base = shard_leaf(leaf, mesh.axes())
    assert allowed.per_chip_bytes == base.per_chip_bytes
    assert allowed.replication == base.replication
