"""Continuous-batching serving engine (the serve data plane's core).

``engine.py`` is the model-agnostic half: an admission loop that
admits waiting requests at EVERY decode step and retires finished
rows immediately (per-row EOS / max-token), so a batch never pads out
to its longest row and a new request's time-to-first-token is one
decode tick + its own prefill instead of a whole preceding
generation.  Two engines share that loop:

* ``SlotEngine`` — the original SLOTS x MAX_LEN slot pool (one
  contiguous KV row per request);
* ``PagedEngine`` — the paged arena (ISSUE 11): block-granular KV
  with per-request page tables (``paging.py``: free-list allocator,
  admission-time page budgeting, refcounted prefix cache), chunked
  prefill interleaved with decode ticks, and read-only shared prompt
  pages — the serving default.

``pool.py`` is the device half: the jitted prefill/decode pair over
the persistent cache (models/decode.py), shared by the single-chip
server and the multi-host gang driver.

``migration.py`` (ISSUE 16) makes the KV page the unit of MOBILITY:
live sessions move pod-to-pod mid-generation under a fenced cutover
protocol — the primitive behind drain-with-migration, prefix-hotspot
rebalancing, and prefill/decode disaggregation.
"""

from dcos_commons_tpu.serve.engine import (
    SERVESTATS_NAME,
    PagedEngine,
    SlotEngine,
    read_servestats,
)
from dcos_commons_tpu.serve.migration import (
    HttpEngineClient,
    InProcessTransport,
    MigrationError,
    MigrationRecord,
    PrefillHandoff,
    ReleasePendingError,
    SessionMigratedError,
    SessionSnapshot,
    SimulatedDcnTransport,
    drain_sessions,
    migrate_session,
)
from dcos_commons_tpu.serve.paging import (
    PageAllocator,
    PagedServeConfig,
    paged_config_from_env,
)

__all__ = [
    "SERVESTATS_NAME",
    "HttpEngineClient",
    "InProcessTransport",
    "MigrationError",
    "MigrationRecord",
    "PageAllocator",
    "PagedEngine",
    "PagedServeConfig",
    "PrefillHandoff",
    "ReleasePendingError",
    "SessionMigratedError",
    "SessionSnapshot",
    "SimulatedDcnTransport",
    "SlotEngine",
    "drain_sessions",
    "migrate_session",
    "paged_config_from_env",
    "read_servestats",
]
