"""Static analysis: the build-gate tooling the reference runs first.

Reference: the root build gates every module on checkstyle/findbugs
before a single test runs (build.gradle's lint plugins — see
tests/test_build_gate.py), and DefaultConfigurationUpdater runs 19
config validators before a target config may go live.  This package
is the code-level analogue for OUR invariants, six analyzers behind
one CLI (``python -m dcos_commons_tpu.analysis``):

- **Framework lint** (`linter`, `rules`, `baseline`): AST rules over
  the whole package — event-loop discipline (no ``time.sleep`` in
  scheduler hot paths), ledger/inventory generation-bump discipline,
  lock discipline, resource vocabulary (no ``gpus``), exception
  swallowing, and JAX tracer safety.  Violations are suppressible
  in-line (``# sdklint: disable=<rule>``) and pre-existing debt is
  tracked in a repo-level baseline file instead of hidden.
- **Lock-order checker** (`lockcheck`): an opt-in instrumented lock
  wrapper that records per-thread acquisition stacks at runtime,
  builds the lock-order graph, and reports cycles (deadlock risk)
  and cross-thread unguarded attribute writes.
- **Spec analyzer** (`speccheck`): a dry-run pass over every
  ``frameworks/*/svc*.yml`` + ``options.json`` that reports
  deploy-time failures at lint time — config-validator errors,
  unsatisfiable placement against the declared torus, conflicting
  ports, plan dependency cycles, and per-host resource overcommit.
- **SPMD collective-safety analyzer** (`spmdcheck`): an
  interprocedural AST pass over the data-plane layers (``parallel/``,
  ``models/``, ``ops/``, ``utils/``, ``storage/``,
  ``frameworks/jax``) that builds per-function collective summaries
  and flags cross-host divergence hazards — collectives under
  host-identity branches, device-varying control flow, unknown mesh
  axes, unordered-iteration schedules, per-host loop trip counts.
- **Sharding analyzer** (`shardcheck`): abstract (shape/dtype-only)
  evaluation of the REAL sharding rules, mesh derivation, and model
  initializers for every ``frameworks/jax`` YAML rendered with its
  options defaults — divisibility of mesh axes into sharded dims,
  unknown PartitionSpec axes, accidentally replicated giant params,
  per-chip/per-host HBM footprint vs the spec's declared budget, and
  a ring-vs-all-gather collective-cost estimate per training step.
- **Plan model checker** (`plancheck`): a bounded explicit-state
  checker that drives the REAL ``plan/`` objects through exhaustive
  BFS over status arrivals, restarts, force-completes, interrupts,
  and dependency unlocks (~10^4 deduped states), verifying
  no-silent-regression, error-absorption, aggregate consistency,
  dependency honoring, interrupt visibility, and livelock freedom —
  violations come back as minimal event traces.
"""

from dcos_commons_tpu.analysis.linter import (  # noqa: F401
    Finding,
    LintContext,
    lint_paths,
    lint_tree,
)
from dcos_commons_tpu.analysis.rules import all_rules  # noqa: F401
