"""Sharded serving gang worker: tp-sharded generate over a multi-host
jax.distributed gang, fronted by rank 0's HTTP server.

The serving half of the flagship at GANG scale: the model's parameters
are tensor-parallel-sharded across every chip of the gang (a model too
big for one host serves from the whole slice), and every request is
executed by ONE pjit'd generate that all ranks enter together.  SPMD
serving needs every process in the collective, but requests arrive
only at the VIP'd rank — so rank 0 broadcasts each request (or an
idle tick) to the gang, everyone steps the same program, and rank 0
replies.  This is the standard multihost serving driver loop; the
single-chip path (serve_worker.py) stays dispatch-free.

Concurrent clients MICRO-BATCH like the single-chip server: the
driver drains same-temperature queued requests into one gang dispatch,
and mixed prompt LENGTHS merge too — the broadcast carries a per-row
true_len vector (models/decode.py per-row path), so heterogeneous
clients share the mesh instead of serializing behind it.

Failover comes from GANG recovery, not from this file: kill any host
and the scheduler replaces the whole gang (tests/test_gang_serve.py
semantics); the replacement re-rendezvouses, rebuilds the identical
tp-sharded params, and greedy replies are token-identical
(tests/test_gang_serve_sharded.py proves it end to end).

Reference: the reference never serves models — its analogue is any
multi-task service behind a VIP (sdk/scheduler
offer/evaluate/PodInfoBuilder VIP labels); the gang/SPMD shape is the
TPU-first addition.
"""

import json
import math
import os
import sys

import numpy as np
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

sys.path.insert(0, os.environ.get("REPO_ROOT", "/root/repo"))

from dcos_commons_tpu.trace.steplog import StepLog  # noqa: E402
from dcos_commons_tpu.utils.microbatch import (  # noqa: E402
    MicroBatcher,
    WorkItem,
    pack_mixed_rows,
    unpack_results,
)

# how often idle ranks meet in a noop collective: the gang must stay
# in lockstep even with no traffic, or a request would wait on ranks
# parked in a stale program
IDLE_TICK_S = 0.05

OP_NOOP = 0
OP_GENERATE = 1


def main() -> int:
    from dcos_commons_tpu.parallel.distributed import initialize_from_env

    contract = initialize_from_env()

    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax.experimental import multihost_utils
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from dcos_commons_tpu.models import (
        config_from_env,
        generate,
        init_params,
    )
    from dcos_commons_tpu.models.transformer import param_shardings
    from dcos_commons_tpu.parallel.mesh import MeshSpec, make_mesh
    from dcos_commons_tpu.utils import (
        enable_compilation_cache,
        restore_checkpoint,
    )

    enable_compilation_cache()
    rank = contract["worker_id"]
    # a RELAUNCH reuses the sandbox: a stale ready file from the
    # previous incarnation must not pass readiness while we are cold
    try:
        os.remove("ready")
    except OSError:
        pass
    config = config_from_env(
        os.environ,
        dtype=jnp.bfloat16 if os.environ.get(
            "JAX_PLATFORMS"
        ) != "cpu" else jnp.float32,
        remat=False,
    )
    max_len = int(os.environ.get("MAX_LEN", "256"))
    batch = int(os.environ.get("SERVE_BATCH", "1"))
    new_tokens = int(os.environ.get("MAX_NEW_TOKENS", "32"))
    prompt_len = max_len - new_tokens

    # the WHOLE gang is one tp axis: the model lives sharded across
    # every chip (ICI within hosts, DCN across under a dcn axis would
    # slot in here for multi-slice; the test gang is one slice)
    n_devices = len(jax.devices())
    mesh = make_mesh(MeshSpec(tp=n_devices))
    with mesh:
        params = init_params(config, jax.random.key(0))
        ckpt_dir = os.environ.get("CHECKPOINT_DIR", "")
        if ckpt_dir:
            state, step = restore_checkpoint(ckpt_dir, {"params": params})
            if step is not None:
                params = state["params"]
                print(f"restored checkpoint step {step}", flush=True)
        params = jax.tree.map(
            jax.device_put, params, param_shardings(config, mesh)
        )
        if os.environ.get("WEIGHT_DTYPE", "native") == "int8":
            # quantize AFTER placement: GSPMD derives the int8/scale
            # shardings from the already-sharded weights, so the
            # {"q","scale"} leaves need no new sharding rules
            from dcos_commons_tpu.models import quantize_params_int8

            params = jax.jit(quantize_params_int8)(params)
            if rank == 0:
                print("weights quantized to int8 (per-channel)", flush=True)
        replicated = NamedSharding(mesh, P())

        def to_global(arr):
            """Identical host-local array on every rank -> one global
            replicated jax array the sharded generate accepts."""
            return multihost_utils.host_local_array_to_global_array(
                arr, mesh, P()
            )

        kv_dtype = os.environ.get("KV_DTYPE", "native")
        gen = jax.jit(
            lambda p, t, seed, temp, lens: generate(
                config, p, t, max_new_tokens=new_tokens, max_len=max_len,
                temperature=temp, key=jax.random.key(seed),
                true_len=lens, kv_dtype=kv_dtype,
            ),
            out_shardings=replicated,
        )

        def run_from_payload(head, lens, prompt_np):
            """Execute the broadcast program: EVERY rank decodes the
            identical payload, so traced operands are byte-identical
            across the gang (diverging scalars would make each rank
            compute a different program's shard).  ``lens`` is the
            PER-ROW true_len vector: mixed-length merged requests ride
            one dispatch (models/decode.py per-row path)."""
            out = gen(
                params,
                to_global(prompt_np.astype(np.int32)),
                np.int64(int(head[2])),
                np.float32(int(head[3]) / 1e6),
                to_global(lens.astype(np.int32)),
            )
            # replicated output: every rank holds the full answer;
            # ONE bulk fetch (per-element reads are ~100ms each over a
            # TPU relay)
            return np.asarray(jax.device_get(out))

        # warm the compiled path as a GANG before readiness: the first
        # request must not pay the compile, and a rank that cannot
        # compile must fail deploy, not the first client
        run_from_payload(
            np.asarray([OP_GENERATE, batch, 0, 0], np.int64),
            np.full((batch,), prompt_len, np.int32),
            np.zeros((batch, prompt_len), np.int32),
        )

        # per-dispatch step telemetry ($SANDBOX/steplog.jsonl): every
        # rank logs each gang generate — wall seconds, rows, and for
        # followers the time spent parked in the broadcast waiting for
        # rank 0 (the serving gang's skew/idle signal).  Surfaced by
        # the scheduler's /v1/debug/trace as one lane per host.
        import time as _time

        steplog = StepLog()
        dispatch_count = [0]

        # Intentional driver/follower split: BOTH sides of this branch
        # run the identical collective sequence (one _broadcast_tick per
        # tick, one gang generate per OP_GENERATE), so the schedules
        # never diverge; the branch only decides who PRODUCES the
        # payload that every rank consumes.
        # sdklint: disable=spmd-host-branch — driver loops meet in the broadcast
        if rank != 0:
            # follower loop: meet rank 0 in every broadcast tick and
            # execute whatever it scheduled
            with open("ready", "w") as f:
                f.write("warm\n")
            print(f"rank {rank}: following gang broadcasts", flush=True)
            while True:
                b0 = _time.time()
                head, lens, prompt = _broadcast_tick(
                    multihost_utils, None, batch, prompt_len
                )
                blocked_s = _time.time() - b0
                if int(head[0]) == OP_GENERATE:
                    t0 = _time.time()
                    run_from_payload(head, lens, prompt)
                    steplog.record(
                        dispatch_count[0],
                        wall_s=round(_time.time() - t0, 6),
                        blocked_s=round(blocked_s, 6),
                        rows=int(head[1]),
                        tokens=int(head[1]) * new_tokens,
                        worker=rank,
                    )
                    dispatch_count[0] += 1

        # ---- rank 0: HTTP front end + the shared micro-batcher ------
        # run_group broadcasts the merged group to the gang (mixed
        # lengths ride the per-row lens vector); on_idle keeps the
        # followers meeting in noop collectives between requests.
        def run_group(group):
            if len(group) > 1:
                print(
                    f"gangbatch: {len(group)} requests / "
                    f"{sum(len(m.rows) for m in group)} rows in one "
                    "gang dispatch",
                    flush=True,
                )
            prompt, lens, used = pack_mixed_rows(
                group, batch, prompt_len
            )
            seed = int.from_bytes(os.urandom(4), "little")
            head = np.asarray([
                OP_GENERATE, used, seed, int(group[0].temp * 1e6),
            ], np.int64)
            head, lens, prompt = _broadcast_tick(
                multihost_utils, (head, lens, prompt),
                batch, prompt_len,
            )
            t0 = _time.time()
            out = run_from_payload(head, lens, prompt)
            steplog.record(
                dispatch_count[0],
                wall_s=round(_time.time() - t0, 6),
                blocked_s=0.0,  # rank 0 paces the gang; it never waits
                rows=used,
                tokens=used * new_tokens,
                worker=0,
            )
            dispatch_count[0] += 1
            unpack_results(group, out)

        def idle_tick():
            _broadcast_tick(multihost_utils, None, batch, prompt_len)

        batcher = MicroBatcher(
            run_group, capacity=batch,
            # default 0: the gang driver loop already paces dispatches
            # (followers meet rank 0 in broadcast ticks), so waiting
            # for joiners only adds latency unless an operator asks
            window_s=float(
                os.environ.get("MICROBATCH_WINDOW_MS", "0")
            ) / 1e3,
            queue_timeout_s=float(
                os.environ.get("SERVE_QUEUE_TIMEOUT_S", "600")
            ),
            on_idle=idle_tick, idle_every_s=IDLE_TICK_S,
        )

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def do_POST(self):
                if self.path != "/generate":
                    self.send_error(404)
                    return
                length = int(self.headers.get("Content-Length", 0))
                try:
                    body = json.loads(self.rfile.read(length))
                    rows = body["tokens"]
                    if len(rows) > batch:
                        raise ValueError(
                            f"{len(rows)} prompts > server batch {batch}"
                        )
                    # rows may have MIXED lengths: the gang dispatch
                    # takes a per-row true_len vector
                    for row in rows:
                        if not 1 <= len(row) <= prompt_len:
                            raise ValueError(
                                f"prompt length must be in "
                                f"[1, {prompt_len}]"
                            )
                    if not rows:
                        raise ValueError("tokens must be non-empty")
                    temp = float(body.get("temperature", 0.0))
                    if not math.isfinite(temp) or not 0.0 <= temp <= 1e4:
                        # bounded: the broadcast head carries the value
                        # as micro-units in an int64 — and a six-digit
                        # temperature is an input error anyway
                        raise ValueError(
                            f"temperature must be in [0, 10000], got {temp}"
                        )
                    n = min(
                        int(body.get("max_new_tokens", new_tokens)),
                        new_tokens,
                    )
                    if n < 1:
                        raise ValueError("max_new_tokens must be >= 1")
                    result = batcher.submit(WorkItem(
                        [[int(t) % config.vocab for t in row]
                         for row in rows],
                        n, temp,
                    ))
                    payload = json.dumps({"tokens": result}).encode()
                    self.send_response(200)
                except Exception as e:  # noqa: BLE001
                    payload = json.dumps({"error": str(e)}).encode()
                    self.send_response(400)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

        port = int(os.environ.get("PORT_HTTP", "0"))
        server = ThreadingHTTPServer(("0.0.0.0", port), Handler)
        with open("ready", "w") as f:
            f.write("warm\n")
        print(
            f"rank 0: serving sharded generate({batch}x{prompt_len}->"
            f"{new_tokens}) tp={n_devices} on {server.server_address[1]}",
            flush=True,
        )
        server.serve_forever()
    return 0


def _broadcast_tick(multihost_utils, payload, batch, prompt_len):
    """One gang-wide broadcast: rank 0 passes (head, lens, prompt),
    the followers pass None and receive rank 0's payload.  head =
    [op, rows_used, seed, temp_micro]; lens is the per-row true_len
    vector (mixed-length merging)."""
    if payload is None:
        payload = (
            np.zeros(4, np.int64),
            np.zeros((batch,), np.int32),
            np.zeros((batch, prompt_len), np.int32),
        )
    head, lens, prompt = multihost_utils.broadcast_one_to_all(payload)
    return np.asarray(head), np.asarray(lens), np.asarray(prompt)


if __name__ == "__main__":
    raise SystemExit(main())
