"""Per-package user options schema: the config.json/Cosmos plane.

Reference: every reference framework ships a
``universe/config.json`` — a typed schema of operator options with
defaults/enums/constraints (frameworks/helloworld/universe/config.json,
488 lines) — which Cosmos validates user options against and renders
into the scheduler's environment (marathon.json.mustache env block);
the sim harness fakes that pipeline with CosmosRenderer
(sdk/testing/.../CosmosRenderer.java:24).

Here the same plane is an ``options.json`` beside ``svc.yml``::

    {
      "properties": {
        "hello": {
          "description": "hello pod settings",
          "properties": {
            "count": {"type": "integer", "default": 2, "minimum": 1,
                      "env": "HELLO_COUNT"},
            "mode":  {"type": "string", "enum": ["blue", "green"],
                      "default": "blue"}
          }
        }
      }
    }

* every leaf option has a ``type`` (string/integer/number/boolean), an
  optional ``default`` (absent + ``"required": true`` = operator must
  supply), optional ``enum``/``minimum``/``maximum`` constraints, and
  an optional ``env`` name (default: ``SECTION_OPTION`` upper-snaked)
  — the rendered env feeds the YAML's ``{{VAR}}`` interpolation;
* ``render_options(schema, user_options)`` is the Cosmos analogue:
  validate the operator's ``{"section": {"option": value}}`` JSON
  against the schema (unknown keys, wrong types, enum/range
  violations are POINTED errors naming the option) and produce the
  env map;
* ``validate_schema(schema)`` lints the schema itself (package build
  and ``package lint`` refuse a package whose defaults don't satisfy
  their own constraints).
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, List, Optional

OPTIONS_FILE = "options.json"

_TYPES = {
    "string": str,
    "boolean": bool,
    "integer": int,
    "number": (int, float),
}


class OptionsError(Exception):
    """User options rejected; ``errors`` lists pointed messages."""

    def __init__(self, errors: List[str]):
        self.errors = list(errors)
        super().__init__("; ".join(self.errors))


def load_schema(framework_dir: str) -> Optional[Dict[str, Any]]:
    """The framework's options.json, or None when it ships none."""
    path = os.path.join(framework_dir, OPTIONS_FILE)
    if not os.path.isfile(path):
        return None
    with open(path, "r", encoding="utf-8") as f:
        try:
            schema = json.load(f)
        except ValueError as e:
            raise OptionsError([f"{OPTIONS_FILE} is not valid JSON: {e}"])
    if not isinstance(schema, dict):
        raise OptionsError([
            f"{OPTIONS_FILE} must be a JSON object, "
            f"got {type(schema).__name__}"
        ])
    return schema


def options_findings(framework_dir: str) -> List[str]:
    """Schema findings for one framework dir — the single check both
    ``package build`` and ``package lint`` run (empty = clean or no
    schema shipped)."""
    try:
        schema = load_schema(framework_dir)
    except OptionsError as e:
        return list(e.errors)
    if schema is None:
        return []
    return [
        f"{OPTIONS_FILE}: {finding}" for finding in validate_schema(schema)
    ]


def default_env_name(section: str, option: str) -> str:
    return re.sub(r"[^A-Z0-9]", "_", f"{section}_{option}".upper())


def _iter_options(schema: Dict[str, Any]):
    for section, sect_raw in (schema.get("properties") or {}).items():
        if not isinstance(sect_raw, dict):
            continue  # validate_schema reports it as a finding
        for option, opt_raw in (sect_raw.get("properties") or {}).items():
            yield section, option, (opt_raw or {})


def validate_schema(schema: Dict[str, Any]) -> List[str]:
    """Schema self-consistency findings (empty = clean)."""
    findings: List[str] = []
    if not isinstance(schema, dict) or \
            not isinstance(schema.get("properties"), dict):
        return ["top-level 'properties' object required"]
    for section, sect_raw in schema["properties"].items():
        if not isinstance(sect_raw, dict) or \
                not isinstance(sect_raw.get("properties"), dict):
            # a misspelled/missing 'properties' would otherwise pass
            # lint and then reject every operator option at install
            findings.append(
                f"section {section!r}: needs a 'properties' object"
            )
    seen_env: Dict[str, str] = {}
    for section, option, opt in _iter_options(schema):
        where = f"{section}.{option}"
        opt_type = opt.get("type")
        if opt_type not in _TYPES:
            findings.append(
                f"{where}: type must be one of {sorted(_TYPES)}, "
                f"got {opt_type!r}"
            )
            continue
        default = opt.get("default")
        if default is None and not opt.get("required"):
            findings.append(
                f"{where}: needs a 'default' or \"required\": true"
            )
        if default is not None:
            errors: List[str] = []
            _check_value(section, option, opt, default, errors)
            findings.extend(f"{e} (the schema's own default)"
                            for e in errors)
        env = opt.get("env") or default_env_name(section, option)
        if env in seen_env:
            findings.append(
                f"{where}: env {env!r} collides with {seen_env[env]}"
            )
        seen_env[env] = where
        if "minimum" in opt and "maximum" in opt and \
                opt["minimum"] > opt["maximum"]:
            findings.append(f"{where}: minimum > maximum")
    return findings


def _check_value(
    section: str, option: str, opt: Dict[str, Any], value: Any,
    errors: List[str],
) -> None:
    where = f"{section}.{option}"
    expected = _TYPES[opt["type"]]
    # bool is an int subclass: reject True for integer/number options
    if isinstance(value, bool) and opt["type"] != "boolean":
        errors.append(
            f"{where}: expected {opt['type']}, got boolean {value!r}"
        )
        return
    if not isinstance(value, expected):
        errors.append(
            f"{where}: expected {opt['type']}, "
            f"got {type(value).__name__} {value!r}"
        )
        return
    enum = opt.get("enum")
    if enum and value not in enum:
        errors.append(f"{where}: {value!r} not one of {enum}")
    try:
        if "minimum" in opt and value < opt["minimum"]:
            errors.append(
                f"{where}: {value!r} below minimum {opt['minimum']}"
            )
        if "maximum" in opt and value > opt["maximum"]:
            errors.append(
                f"{where}: {value!r} above maximum {opt['maximum']}"
            )
    except TypeError:
        # the CONSTRAINT doesn't fit the type (e.g. minimum on a
        # string): a schema bug, reported as a finding not a crash
        errors.append(
            f"{where}: minimum/maximum not comparable with "
            f"{opt['type']} values"
        )


def _render_value(value: Any) -> str:
    if isinstance(value, bool):
        return "true" if value else "false"
    return str(value)


def render_options(
    schema: Optional[Dict[str, Any]],
    user_options: Optional[Dict[str, Any]],
) -> Dict[str, str]:
    """The Cosmos render: defaults overlaid with the operator's
    options, validated, flattened to the env map the YAML interpolates.

    Raises OptionsError with every violation (not just the first) so
    the operator fixes the options file in one pass."""
    user_options = user_options or {}
    if schema is None:
        if user_options:
            raise OptionsError([
                "this package ships no options.json; options "
                f"{sorted(user_options)} cannot be applied"
            ])
        return {}
    errors: List[str] = []
    known = {
        (section, option): opt
        for section, option, opt in _iter_options(schema)
    }
    known_by_section: Dict[str, List[str]] = {}
    for section, option in known:
        known_by_section.setdefault(section, []).append(option)
    # unknown keys are pointed errors (a typo must not silently fall
    # back to the default)
    for section, sect_value in user_options.items():
        if section not in known_by_section:
            errors.append(
                f"no such options section {section!r}; known: "
                f"{sorted(known_by_section)}"
            )
            continue
        if not isinstance(sect_value, dict):
            errors.append(f"options section {section!r} must be an object")
            continue
        for option in sect_value:
            if (section, option) not in known:
                errors.append(
                    f"no such option {section}.{option}; known: "
                    + ", ".join(
                        f"{section}.{o}"
                        for o in sorted(known_by_section[section])
                    )
                )
    env: Dict[str, str] = {}
    for (section, option), opt in sorted(known.items()):
        provided = user_options.get(section, {})
        if isinstance(provided, dict) and option in provided:
            value = provided[option]
            _check_value(section, option, opt, value, errors)
        elif "default" in opt:
            value = opt["default"]
        else:  # required, not provided
            errors.append(
                f"{section}.{option} is required and has no default"
            )
            continue
        env[opt.get("env") or default_env_name(section, option)] = \
            _render_value(value)
    if errors:
        raise OptionsError(errors)
    return env


def prune_unknown(
    schema: Optional[Dict[str, Any]],
    options: Optional[Dict[str, Any]],
) -> tuple:
    """(kept, dropped) — options the schema still defines vs ones it
    no longer knows.  Used on PRIOR (stored) options at upgrade time:
    a new package version that drops an option must not be blocked
    forever by the stored value (the strict unknown-key rejection
    stays for freshly-PASSED options, where unknown = typo)."""
    options = options or {}
    if schema is None:
        return {}, sorted(
            f"{s}.{o}" for s, v in options.items()
            for o in (v if isinstance(v, dict) else {""})
        )
    known = {
        (section, option)
        for section, option, _ in _iter_options(schema)
    }
    kept: Dict[str, Any] = {}
    dropped: List[str] = []
    for section, sect_value in options.items():
        if not isinstance(sect_value, dict):
            dropped.append(section)
            continue
        for option, value in sect_value.items():
            if (section, option) in known:
                kept.setdefault(section, {})[option] = value
            else:
                dropped.append(f"{section}.{option}")
    return kept, sorted(dropped)


def merge_options(
    base: Optional[Dict[str, Any]],
    override: Optional[Dict[str, Any]],
) -> Dict[str, Any]:
    """Per-section merge for upgrades: Cosmos `update` keeps prior
    options and overlays the newly-passed ones."""
    out: Dict[str, Any] = {
        k: dict(v) if isinstance(v, dict) else v
        for k, v in (base or {}).items()
    }
    for section, sect_value in (override or {}).items():
        if isinstance(sect_value, dict) and \
                isinstance(out.get(section), dict):
            out[section].update(sect_value)
        else:
            out[section] = sect_value
    return out
