"""Offer-cycle fast path tests (ISSUE 1 tentpole).

Three properties are load-bearing:

1. snapshot-cache EQUIVALENCE: cached ``SliceInventory.snapshots``
   must be bit-identical to a from-scratch rebuild under randomized
   reservation commit/GC/host up-down interleavings — the cache is a
   pure memo, never a semantic change.
2. event-driven scheduling: a multi-step deploy completes in well
   under ``steps x interval_s`` when statuses nudge the loop, with the
   interval demoted to a fallback heartbeat.
3. cycle observability: the new timer aggregates and cache counters
   surface through the existing metrics snapshot.
"""

import random
import threading
import time

from dcos_commons_tpu.common import TaskState, TaskStatus
from dcos_commons_tpu.metrics.registry import Metrics
from dcos_commons_tpu.offer import (
    Reservation,
    ReservationLedger,
    SliceInventory,
    TpuHost,
)
from dcos_commons_tpu.offer.inventory import make_test_fleet
from dcos_commons_tpu.offer.ledger import new_reservation_id
from dcos_commons_tpu.scheduler import SchedulerBuilder, SchedulerConfig
from dcos_commons_tpu.specification import from_yaml
from dcos_commons_tpu.storage import MemPersister
from dcos_commons_tpu.testing import FakeAgent

# -- snapshot cache ---------------------------------------------------


def canonical(snapshots):
    """Order-insensitive, content-complete form of a snapshot list."""
    return sorted(
        (
            s.host.host_id,
            round(s.cpus, 9),
            s.memory_mb,
            s.disk_mb,
            tuple(sorted(s.free_chips)),
            tuple(sorted(s.used_ports)),
        )
        for s in snapshots
    )


def random_reservation(rng, hosts):
    host = rng.choice(hosts)
    chips = host.chip_ids()
    return Reservation(
        reservation_id=new_reservation_id(),
        host_id=host.host_id,
        task_name=f"pod-{rng.randrange(64)}-server",
        cpus=rng.choice([0.5, 1.0, 2.0]),
        memory_mb=rng.choice([256, 1024]),
        disk_mb=rng.choice([0, 512]),
        chip_ids=rng.sample(chips, rng.randrange(len(chips) + 1)) if chips else [],
        ports=rng.sample(range(10000, 10050), rng.randrange(3)),
    )


def test_snapshot_cache_equivalence_randomized():
    """Cached vs from-scratch snapshots stay identical across 400
    randomized ledger/host mutations (the tentpole correctness bar)."""
    rng = random.Random(20260803)
    hosts = make_test_fleet(host_grid=(4, 2), chip_block=(2, 2))
    hosts += [TpuHost(host_id=f"cpu-{i}") for i in range(4)]
    ledger = ReservationLedger(MemPersister())
    inv = SliceInventory(hosts)  # cached across the whole interleaving
    down = set()

    for step in range(400):
        op = rng.random()
        if op < 0.45:
            ledger.commit([
                random_reservation(rng, hosts)
                for _ in range(rng.randrange(1, 4))
            ])
        elif op < 0.75:
            live = ledger.all()
            if live:
                ledger.release(rng.choice(live).reservation_id)
        elif op < 0.9:
            host = rng.choice(hosts)
            inv.mark_down(host.host_id)
            down.add(host.host_id)
        else:
            if down:
                host_id = down.pop()
                inv.mark_up(host_id)
        # a fresh inventory has an empty cache: its snapshots are the
        # from-scratch oracle for the SAME hosts/down-set/ledger
        oracle = SliceInventory(hosts)
        for host_id in down:
            oracle.mark_down(host_id)
        assert canonical(inv.snapshots(ledger)) == canonical(
            oracle.snapshots(ledger)
        ), f"cached snapshots diverged at step {step}"
    assert inv.cache_hits > 0  # the interleaving actually exercised reuse


def test_snapshot_cache_hits_when_ledger_quiet():
    ledger = ReservationLedger(MemPersister())
    inv = SliceInventory(make_test_fleet(host_grid=(2, 2)))
    inv.snapshots(ledger)
    assert inv.cache_misses == 4 and inv.cache_hits == 0
    inv.snapshots(ledger)
    assert inv.cache_hits == 4
    # a commit dirties exactly the touched host
    fleet_host = inv.hosts()[0]
    ledger.commit([
        Reservation(
            reservation_id=new_reservation_id(),
            host_id=fleet_host.host_id,
            task_name="t-0-x",
            cpus=1.0,
        )
    ])
    inv.snapshots(ledger)
    assert inv.cache_misses == 5  # one rebuild, three reuses
    assert inv.cache_hits == 7


def test_snapshot_cache_returns_copies():
    """Callers may mutate returned snapshots freely — the cached
    master must not be poisoned."""
    ledger = ReservationLedger(MemPersister())
    inv = SliceInventory(make_test_fleet(host_grid=(1, 1)))
    first = inv.snapshots(ledger)[0]
    first.try_consume_scalar(10.0, 1000, 0)
    first.free_chips.clear()
    first.allocate_port()
    again = inv.snapshots(ledger)[0]
    assert again.cpus == 16.0
    assert len(again.free_chips) == 4
    assert not again.used_ports


def test_chip_ids_memoized_and_stable():
    host = make_test_fleet(host_grid=(2, 2), chip_block=(2, 2))[3]
    first = host.chip_ids()
    assert first == ["pod-0/2,2", "pod-0/3,2", "pod-0/2,3", "pod-0/3,3"]
    first.append("tampered")  # callers get an independent list
    assert host.chip_ids() == ["pod-0/2,2", "pod-0/3,2", "pod-0/2,3",
                               "pod-0/3,3"]


def test_ledger_generation_tracking():
    ledger = ReservationLedger(MemPersister())
    assert ledger.host_generation("h1") == 0
    r = Reservation(
        reservation_id=new_reservation_id(), host_id="h1",
        task_name="p-0-t", cpus=1.0,
    )
    ledger.commit([r])
    g1 = ledger.host_generation("h1")
    assert g1 > 0 and ledger.host_generation("h2") == 0
    ledger.release(r.reservation_id)
    assert ledger.host_generation("h1") > g1
    assert ledger.reserved_on("h1") == []
    assert ledger.for_task("p-0-t") == []


# -- event-driven scheduling ------------------------------------------

SERIAL_YAML = """
name: steps
pods:
  app:
    count: 3
    tasks:
      server:
        goal: RUNNING
        cmd: sleep 1000
        cpus: 1.0
        memory: 256
plans:
  deploy:
    strategy: serial
    phases:
      app:
        strategy: serial
        pod: app
"""


def _build_serial_scheduler():
    builder = SchedulerBuilder(
        from_yaml(SERIAL_YAML),
        SchedulerConfig(backoff_enabled=False),
        MemPersister(),
    )
    builder.set_inventory(SliceInventory(
        [TpuHost(host_id=f"h{i}") for i in range(3)]
    ))
    agent = FakeAgent()
    builder.set_agent(agent)
    return builder.build(), agent


def test_event_driven_wake_beats_fallback_interval():
    """A 3-step serial plan with a 5 s fallback heartbeat completes in
    well under 3 x 5 s because status arrival nudges the loop: the
    interval is a heartbeat, not a pace."""
    scheduler, agent = _build_serial_scheduler()
    interval_s = 5.0
    acked = set()
    stop = threading.Event()

    def responder():
        while not stop.is_set():
            for info in list(agent.launched):
                if info.task_id not in acked:
                    acked.add(info.task_id)
                    agent.send(TaskStatus(
                        task_id=info.task_id, state=TaskState.RUNNING,
                        ready=True, agent_id=info.agent_id,
                    ))
            time.sleep(0.005)

    responder_thread = threading.Thread(target=responder, daemon=True)
    responder_thread.start()
    t0 = time.monotonic()
    loop_thread = scheduler.run_forever(interval_s=interval_s)
    try:
        deadline = t0 + 10.0
        while time.monotonic() < deadline and \
                not scheduler.deploy_manager.get_plan().is_complete:
            time.sleep(0.01)
        elapsed = time.monotonic() - t0
        assert scheduler.deploy_manager.get_plan().is_complete, \
            "serial deploy did not complete"
        # << 3 x 5 s; generous bound for slow CI boxes
        assert elapsed < interval_s, (
            f"3-step plan took {elapsed:.2f}s — the loop slept through "
            "its fallback interval instead of waking on events"
        )
        assert scheduler.metrics.counters().get("cycle.nudges", 0) > 0
    finally:
        stop.set()
        scheduler.stop()
        loop_thread.join(timeout=2)
        responder_thread.join(timeout=2)


def test_nudge_wakes_idle_loop():
    """An idle (suppressed) loop parked in a long fallback wait runs a
    cycle promptly after nudge() — the HTTP-mutation wake path."""
    scheduler, agent = _build_serial_scheduler()
    # complete the deploy synchronously first
    for _ in range(6):
        scheduler.run_cycle()
        for info in list(agent.launched):
            agent.send(TaskStatus(
                task_id=info.task_id, state=TaskState.RUNNING, ready=True,
                agent_id=info.agent_id,
            ))
    scheduler.run_cycle()
    assert scheduler.deploy_manager.get_plan().is_complete
    baseline = scheduler.metrics.counters().get("task_status.TASK_KILLED", 0)
    loop_thread = scheduler.run_forever(interval_s=30.0)
    try:
        time.sleep(0.2)  # the loop is now parked in its 30 s wait
        t0 = time.monotonic()
        scheduler.restart_pod("app", 0)  # kills + nudges
        while time.monotonic() - t0 < 5.0:
            if scheduler.metrics.counters().get(
                "task_status.TASK_KILLED", 0
            ) > baseline:
                break
            time.sleep(0.01)
        waited = time.monotonic() - t0
        assert waited < 5.0, "nudge did not wake the parked loop"
    finally:
        scheduler.stop()
        loop_thread.join(timeout=2)


# -- metrics aggregation ----------------------------------------------


def test_timer_aggregates_min_mean_max_p95():
    metrics = Metrics()
    with metrics.time("cycle.process"):
        pass
    # deterministic samples through the same ring buffer the context
    # manager feeds
    with metrics._lock:
        metrics._timers["cycle.process"] = [0.010, 0.020, 0.030, 0.040]
    snap = metrics.snapshot()
    assert snap["cycle.process.count"] == 4.0
    assert abs(snap["cycle.process.min_s"] - 0.010) < 1e-9
    assert abs(snap["cycle.process.mean_s"] - 0.025) < 1e-9
    assert snap["cycle.process.avg_s"] == snap["cycle.process.mean_s"]
    assert abs(snap["cycle.process.max_s"] - 0.040) < 1e-9
    # nearest-rank p95 of 4 samples = the max
    assert abs(snap["cycle.process.p95_s"] - 0.040) < 1e-9


def test_cycle_metrics_surface_in_snapshot():
    scheduler, agent = _build_serial_scheduler()
    scheduler.run_cycle()
    snap = scheduler.metrics.snapshot()
    assert "offers.snapshot_cache.hit" in snap
    assert "offers.snapshot_cache.miss" in snap
    assert snap["offers.snapshot_cache.miss"] > 0
    assert "cycle.process.p95_s" in snap
    assert "cycle.evaluate.mean_s" in snap
    assert "cycle.snapshot.mean_s" in snap
