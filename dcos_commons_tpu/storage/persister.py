"""Persister contract + in-memory implementation.

Reference: sdk/scheduler/.../storage/Persister.java:15-99 (get/set/
setMany/getChildren/recursiveDelete/close), MemPersister.java,
PersisterUtils path helpers.
"""

from __future__ import annotations

import threading
from abc import ABC, abstractmethod
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Union


class StorageError(Exception):
    """Base class for storage failures."""


class PersisterError(StorageError):
    """A path was missing or an operation conflicted."""

    def __init__(self, message: str, path: str = ""):
        super().__init__(message)
        self.path = path


def normalize_path(path: str) -> str:
    """Canonical form: leading '/', no trailing '/', no empty segments.

    Reference: storage/PersisterUtils.java path math.
    """
    parts = [p for p in path.split("/") if p]
    return "/" + "/".join(parts)


def parent_of(path: str) -> str:
    path = normalize_path(path)
    head, _, _ = path.rpartition("/")
    return head or "/"


def child_of(path: str, *names: str) -> str:
    return normalize_path("/".join([path, *names]))


def namespace_root(namespace: str) -> str:
    """Root prefix for a (possibly multi-segment) service namespace.

    Reference: SchedulerBuilder namespacing for multi-service mode.
    """
    return f"/{namespace.strip('/')}" if namespace else ""


def validate_key(key: str, what: str = "key") -> str:
    """Reject keys that would traverse or collapse storage paths."""
    if not key or "/" in key:
        raise PersisterError(f"invalid {what}: {key!r}")
    return key


@dataclass(frozen=True)
class SetOp:
    path: str
    value: bytes


@dataclass(frozen=True)
class DeleteOp:
    path: str  # recursive


TransactionOp = Union[SetOp, DeleteOp]


class Persister(ABC):
    """Hierarchical path -> bytes store with atomic transactions.

    Intermediate nodes are created implicitly on set (as the reference's
    CuratorPersister does via creatingParentsIfNeeded) and may hold data
    themselves.
    """

    @abstractmethod
    def get(self, path: str) -> Optional[bytes]:
        """Value at ``path``; raises PersisterError if path absent."""

    @abstractmethod
    def set(self, path: str, value: bytes) -> None: ...

    @abstractmethod
    def get_children(self, path: str) -> List[str]:
        """Immediate child names (not full paths); PersisterError if absent."""

    @abstractmethod
    def recursive_delete(self, path: str) -> None:
        """Delete subtree; PersisterError if absent."""

    @abstractmethod
    def apply(self, ops: Iterable[TransactionOp]) -> None:
        """Apply all ops atomically (all-or-nothing).

        Reference: CuratorPersister.java:86-110 atomic multi-op
        transactions; this is what makes launch WAL + status writes
        crash-consistent.
        """

    def close(self) -> None:  # pragma: no cover - trivial default
        pass

    # convenience -----------------------------------------------------

    def get_or_none(self, path: str) -> Optional[bytes]:
        """Value at ``path``, or None when the path is absent."""
        try:
            return self.get(path)
        except PersisterError:
            return None

    def exists(self, path: str) -> bool:
        try:
            self.get(path)
            return True
        except PersisterError:
            return False

    def get_children_or_empty(self, path: str) -> List[str]:
        try:
            return self.get_children(path)
        except PersisterError:
            return []

    def clear_all_data(self) -> None:
        """Reference: storage/PersisterUtils.java clearAllData (uninstall)."""
        for child in self.get_children_or_empty("/"):
            self.recursive_delete("/" + child)


def wipe_namespace(persister: Persister, namespace: str = "") -> None:
    """Delete every node a service owns: its namespace subtree, or —
    for a standalone service — the whole tree MINUS cluster
    infrastructure.  The storage-layer home for the uninstall
    teardown's raw mutation (scheduler paths must not mutate
    persisters directly — sdklint lease-gated-mutation).

    ``/__ha__`` (the leader-lease records) is never wiped: an HA
    uninstaller writes through the lease-fenced persister, and
    deleting its own lease mid-wipe would fence every remaining
    delete — the uninstall could never finish.  The lease expires on
    its own once the process exits."""
    root = namespace_root(namespace)
    if root:
        try:
            persister.recursive_delete(root)
        except PersisterError:
            pass  # already gone
    else:
        for child in persister.get_children_or_empty("/"):
            if child == "__ha__":
                continue
            try:
                persister.recursive_delete(f"/{child}")
            except PersisterError:
                pass  # concurrent cleanup: already gone


class _Node:
    __slots__ = ("value", "children")

    def __init__(self) -> None:
        self.value: Optional[bytes] = None
        self.children: Dict[str, "_Node"] = {}


class MemPersister(Persister):
    """In-memory tree store (reference: storage/MemPersister.java).

    Used by unit tests and the simulation harness exactly as the
    reference uses MemPersister in place of ZooKeeper.
    """

    def __init__(self) -> None:
        self._root = _Node()
        self._lock = threading.RLock()

    # tree walking ----------------------------------------------------

    def _find(self, path: str) -> Optional[_Node]:
        node = self._root
        for part in normalize_path(path).split("/"):
            if not part:
                continue
            node = node.children.get(part)  # type: ignore[assignment]
            if node is None:
                return None
        return node

    def _ensure(self, path: str) -> _Node:
        node = self._root
        for part in normalize_path(path).split("/"):
            if not part:
                continue
            node = node.children.setdefault(part, _Node())
        return node

    # Persister -------------------------------------------------------

    def get(self, path: str) -> Optional[bytes]:
        with self._lock:
            node = self._find(path)
            if node is None:
                raise PersisterError(f"path not found: {path}", path)
            return node.value

    def set(self, path: str, value: bytes) -> None:
        if normalize_path(path) == "/":
            # the root carries no value: dump()/snapshots only cover
            # children, so a root value would silently vanish across
            # compaction — forbid it outright
            raise PersisterError("cannot store a value at '/'", path)
        with self._lock:
            self._ensure(path).value = value

    def ensure_node(self, path: str) -> None:
        """Create an empty node (tree shape without a value)."""
        with self._lock:
            self._ensure(path)

    def get_children(self, path: str) -> List[str]:
        with self._lock:
            node = self._find(path)
            if node is None:
                raise PersisterError(f"path not found: {path}", path)
            return sorted(node.children)

    def recursive_delete(self, path: str) -> None:
        with self._lock:
            norm = normalize_path(path)
            if norm == "/":
                self._root = _Node()
                return
            parent = self._find(parent_of(norm))
            name = norm.rsplit("/", 1)[1]
            if parent is None or name not in parent.children:
                raise PersisterError(f"path not found: {path}", path)
            del parent.children[name]

    def apply(self, ops: Iterable[TransactionOp]) -> None:
        with self._lock:
            ops = list(ops)
            # validate up front so the transaction is all-or-nothing
            for op in ops:
                if isinstance(op, DeleteOp) and self._find(op.path) is None:
                    raise PersisterError(f"path not found: {op.path}", op.path)
                if isinstance(op, SetOp) and normalize_path(op.path) == "/":
                    raise PersisterError("cannot store a value at '/'", op.path)
            for op in ops:
                if isinstance(op, SetOp):
                    self._ensure(op.path).value = op.value
                else:
                    try:
                        self.recursive_delete(op.path)
                    except PersisterError:
                        pass  # deleted by an earlier op in this txn

    # debugging -------------------------------------------------------

    def dump(self) -> Dict[str, Optional[bytes]]:
        """Flat {path: value} view of the whole tree (tests)."""
        out: Dict[str, Optional[bytes]] = {}

        def walk(node: _Node, path: str) -> None:
            for name, child in node.children.items():
                child_path = f"{path}/{name}" if path != "/" else f"/{name}"
                out[child_path] = child.value
                walk(child, child_path)

        with self._lock:
            walk(self._root, "/")
        return out
