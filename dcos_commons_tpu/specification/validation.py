"""Config-update validators: what may change between target configs.

Reference: sdk/scheduler/.../config/validate/ (19 validator classes,
run by DefaultConfigurationUpdater.updateConfiguration,
config/DefaultConfigurationUpdater.java:159).  Each validator compares
the previous target spec against the candidate and emits errors; any
error keeps the old target active and surfaces via /v1/plans errors.

TPU-first addition: TpuTopologyCannotChange — you cannot reshape a
live slice's ICI topology by rolling update; that requires pod
replace (SURVEY.md section 2 build plan stage 2).
"""

from __future__ import annotations

from typing import Callable, List, Optional

from dcos_commons_tpu.specification.specs import ServiceSpec


class ConfigValidationError(Exception):
    def __init__(self, errors: List[str]):
        super().__init__("; ".join(errors))
        self.errors = errors


Validator = Callable[[Optional[ServiceSpec], ServiceSpec], List[str]]


def service_name_cannot_change(old, new):
    """Reference: config/validate/ServiceNameCannotContainDoubleUnderscores
    + the implicit identity check in DefaultConfigurationUpdater."""
    errs = []
    if "__" in new.name:
        errs.append(f"service name {new.name!r} may not contain '__'")
    if old is not None and old.name != new.name:
        errs.append(f"service name cannot change: {old.name!r} -> {new.name!r}")
    return errs


def user_cannot_change(old, new):
    """Reference: config/validate/UserCannotChange.java."""
    if old is not None and old.user and old.user != new.user:
        return [f"user cannot change: {old.user!r} -> {new.user!r}"]
    return []


def region_cannot_change(old, new):
    """Reference: config/validate/RegionCannotChange.java."""
    if old is not None and old.region != new.region:
        return [f"region cannot change: {old.region!r} -> {new.region!r}"]
    return []


def pod_specs_cannot_shrink(old, new):
    """Reference: config/validate/PodSpecsCannotShrink.java — pod count
    may only shrink via explicit decommission (allow_decommission)."""
    errs = []
    if old is None:
        return errs
    new_pods = {p.type: p for p in new.pods}
    for old_pod in old.pods:
        new_pod = new_pods.get(old_pod.type)
        if new_pod is None:
            if not old_pod.allow_decommission:
                errs.append(f"pod {old_pod.type!r} cannot be removed")
        elif new_pod.count < old_pod.count and not old_pod.allow_decommission:
            errs.append(
                f"pod {old_pod.type!r} count cannot shrink "
                f"{old_pod.count} -> {new_pod.count} without allow-decommission"
            )
    return errs


def task_volumes_cannot_change(old, new):
    """Reference: config/validate/TaskVolumesCannotChange.java."""
    errs = []
    if old is None:
        return errs
    new_pods = {p.type: p for p in new.pods}
    for old_pod in old.pods:
        new_pod = new_pods.get(old_pod.type)
        if new_pod is None:
            continue
        if tuple(old_pod.volumes) != tuple(new_pod.volumes):
            errs.append(f"pod {old_pod.type!r} volumes cannot change")
        old_tasks = {t.name: t for t in old_pod.tasks}
        for new_task in new_pod.tasks:
            old_task = old_tasks.get(new_task.name)
            if old_task and tuple(old_task.volumes) != tuple(new_task.volumes):
                errs.append(
                    f"task {old_pod.type}-{new_task.name} volumes cannot change"
                )
    return errs


def tpu_topology_cannot_change(old, new):
    """TPU-first: the ICI topology of a live pod cannot change by
    rolling update — a pjit mesh is one XLA program over a fixed
    device mesh.  Changing generation/topology requires pod replace."""
    errs = []
    if old is None:
        return errs
    new_pods = {p.type: p for p in new.pods}
    for old_pod in old.pods:
        new_pod = new_pods.get(old_pod.type)
        if new_pod is None or old_pod.tpu is None:
            continue
        if new_pod.tpu is None:
            errs.append(f"pod {old_pod.type!r} cannot drop its tpu block")
        elif (
            old_pod.tpu.generation != new_pod.tpu.generation
            or old_pod.tpu.topology != new_pod.tpu.topology
        ):
            errs.append(
                f"pod {old_pod.type!r} TPU topology cannot change "
                f"({old_pod.tpu.generation}/{old_pod.tpu.topology} -> "
                f"{new_pod.tpu.generation}/{new_pod.tpu.topology}); "
                "use pod replace"
            )
    return errs


def gang_pods_need_topology(old, new):
    """A gang pod with a multi-host topology must have count matching
    the topology's host count (total_chips / chips_per_host)."""
    errs = []
    for pod in new.pods:
        if pod.tpu is None or not pod.tpu.topology:
            continue
        total = pod.tpu.total_chips
        per_host = pod.tpu.chips_per_host
        if total % per_host != 0:
            errs.append(
                f"pod {pod.type!r}: topology {pod.tpu.topology} total chips "
                f"{total} not divisible by chips-per-host {per_host}"
            )
            continue
        hosts = total // per_host
        if pod.count != hosts:
            errs.append(
                f"pod {pod.type!r}: count {pod.count} != {hosts} hosts implied "
                f"by topology {pod.tpu.topology} at {per_host} chips/host"
            )
    return errs


def placement_rules_must_parse(old, new):
    """A bad placement string is a CONFIG error, not a runtime crash in
    the offer cycle (reference: InvalidPlacementRule records parse
    failures so the scheduler surfaces them instead of wedging)."""
    from dcos_commons_tpu.offer.placement import parse_placement

    errs = []
    for pod in new.pods:
        try:
            parse_placement(pod.placement)
        except ValueError as e:
            errs.append(f"pod {pod.type!r}: bad placement: {e}")
    return errs


def default_validators() -> List[Validator]:
    return [
        service_name_cannot_change,
        user_cannot_change,
        region_cannot_change,
        pod_specs_cannot_shrink,
        task_volumes_cannot_change,
        tpu_topology_cannot_change,
        gang_pods_need_topology,
        placement_rules_must_parse,
    ]


def validate_spec_change(
    old: Optional[ServiceSpec],
    new: ServiceSpec,
    validators: Optional[List[Validator]] = None,
) -> None:
    """Run all validators; raise ConfigValidationError on any failure.

    Reference: DefaultConfigurationUpdater.updateConfiguration flow —
    validation errors keep the previous target config active.
    """
    errors: List[str] = []
    for validator in validators if validators is not None else default_validators():
        errors.extend(validator(old, new))
    if errors:
        raise ConfigValidationError(errors)
