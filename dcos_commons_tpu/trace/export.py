"""Trace exporters: Chrome trace-event JSON and a plain-text timeline.

Chrome format (Perfetto/chrome://tracing loadable): one complete
("ph": "X") event per span, ``pid`` = service lane, ``tid`` = the
span's track (a pod instance like "trainer-2", "scheduler", "plan"),
timestamps in wall microseconds.  Worker steplogs merge in as extra
events on ``<task>/steps`` lanes, so a 4-host gang renders as four
step rows whose horizontal offsets ARE the gang skew.

The text form is the ssh-and-curl view: one line per span, sorted by
start, offsets relative to the first span.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

from dcos_commons_tpu.trace.recorder import TraceRecorder
from dcos_commons_tpu.trace.span import render_id

Steplogs = Dict[str, List[dict]]


def to_chrome(
    recorder: TraceRecorder,
    service: str = "scheduler",
    steplogs: Optional[Steplogs] = None,
) -> dict:
    """Chrome trace-event JSON object (round-trips ``json.loads``)."""
    service = service or recorder.service or "scheduler"
    events = []
    for span in recorder.snapshot():
        start_wall = recorder.wall_of(span.start_s)
        args = span.str_attrs()
        args["trace_id"] = render_id(span.trace_id)
        args["span_id"] = render_id(span.span_id)
        if span.parent_id:
            args["parent_id"] = render_id(span.parent_id)
        events.append({
            "name": span.name,
            "ph": "X",
            "pid": service,
            "tid": span.track or "scheduler",
            "ts": int(start_wall * 1e6),
            "dur": max(1, int(span.duration_s * 1e6)),
            "args": args,
        })
    for task_name, records in sorted((steplogs or {}).items()):
        for record in records:
            wall_s = float(record.get("wall_s", 0.0) or 0.0)
            end_wall = float(record.get("t", 0.0) or 0.0)
            events.append({
                "name": f"step {record.get('step', '?')}",
                "ph": "X",
                "pid": service,
                "tid": f"{task_name}/steps",
                "ts": int((end_wall - wall_s) * 1e6),
                "dur": max(1, int(wall_s * 1e6)),
                "args": {
                    k: v for k, v in record.items() if k not in ("t",)
                },
            })
    events.sort(key=lambda e: e["ts"])
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "service": service,
            "spans": len(recorder.snapshot()),
            "dropped": recorder.dropped,
        },
    }


def to_text(
    recorder: TraceRecorder,
    service: str = "scheduler",
    steplogs: Optional[Steplogs] = None,
    events: Optional[List[dict]] = None,
) -> str:
    """Human timeline: offset, duration, trace prefix, lane, name,
    attrs — one line per span/step, sorted by start.  ``events``
    (journal records from the health plane) render on a ``journal``
    lane, so operator verbs / failovers / detector alerts line up
    against the spans around them."""
    rows = []  # (wall_start, dur_s, trace, track, name, attrs)
    for span in recorder.snapshot():
        rows.append((
            recorder.wall_of(span.start_s),
            span.duration_s,
            # the distinct tail of the full id (the leading 8 chars are
            # the shared process prefix): greppable here AND a suffix
            # match for the full ids in the Chrome export
            render_id(span.trace_id)[-8:],
            span.track or "scheduler",
            span.name,
            span.str_attrs(),
        ))
    for task_name, records in sorted((steplogs or {}).items()):
        for record in records:
            wall_s = float(record.get("wall_s", 0.0) or 0.0)
            end_wall = float(record.get("t", 0.0) or 0.0)
            attrs = {k: v for k, v in record.items() if k not in ("t", "step")}
            rows.append((
                end_wall - wall_s,
                wall_s,
                "steplog",
                f"{task_name}/steps",
                f"step {record.get('step', '?')}",
                attrs,
            ))
    for event in events or []:
        attrs = {
            k: v for k, v in event.items()
            if k not in ("t", "kind", "seq", "message")
        }
        if event.get("message"):
            attrs["msg"] = event["message"]
        rows.append((
            float(event.get("t", 0.0) or 0.0),
            0.0,
            f"j{event.get('seq', '?')}",
            "journal",
            str(event.get("kind", "event")),
            attrs,
        ))
    rows.sort(key=lambda r: r[0])
    base = rows[0][0] if rows else 0.0
    lines = [
        f"# trace: {len(rows)} entries "
        f"({recorder.dropped} dropped from the ring buffer), "
        f"service={service or recorder.service or 'scheduler'}",
        "#   offset     duration  trace    lane                 name  attrs",
    ]
    for wall_start, dur_s, trace, track, name, attrs in rows:
        attr_text = " ".join(
            f"{k}={v}" for k, v in sorted(attrs.items())
        )
        lines.append(
            f"{wall_start - base:+10.3f}s {dur_s:9.6f}s {trace:<8} "
            f"{track:<20} {name} {attr_text}".rstrip()
        )
    return "\n".join(lines) + "\n"


def chrome_json(recorder: TraceRecorder, **kwargs) -> str:
    """Serialized convenience wrapper (CLI/file dumps)."""
    return json.dumps(to_chrome(recorder, **kwargs), indent=2)
