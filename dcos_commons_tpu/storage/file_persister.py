"""Write-ahead-logged file persister.

The durability substrate replacing the reference's ZooKeeper
(curator/CuratorPersister.java:43-110).  ZooKeeper gives the reference
atomic multi-op transactions + durability; we get the same from a
single fsync'd append-only log with CRC-framed records and periodic
snapshot compaction.  A TPU pod's control plane runs on one admin VM,
so a local WAL (optionally on replicated storage) is the idiomatic
equivalent; the Persister interface stays pluggable for etcd.

Record framing:  [u32 len][u32 crc32][payload]  where payload is a
JSON-encoded transaction (list of set/delete ops, values hex-encoded).
A torn final record (crash mid-append) is detected by length/CRC and
discarded on replay — the same "WAL before accept" crash-consistency
the reference gets from ZK (state/PersistentLaunchRecorder.java flow,
DefaultScheduler.java:454-455).
"""

from __future__ import annotations

import json
import os
import struct
import threading
import zlib
from typing import Iterable, List, Optional

from dcos_commons_tpu.storage.persister import (
    DeleteOp,
    MemPersister,
    Persister,
    PersisterError,
    SetOp,
    TransactionOp,
    normalize_path,
)

_HEADER = struct.Struct("<II")  # (length, crc32)


class FileWalPersister(Persister):
    """Durable Persister over <dir>/wal.log + <dir>/snapshot.json."""

    SNAPSHOT = "snapshot.json"
    WAL = "wal.log"

    def __init__(self, directory: str, fsync: bool = True,
                 compact_every: int = 4096) -> None:
        self._dir = directory
        self._fsync = fsync
        self._compact_every = compact_every
        self._lock = threading.RLock()
        self._mem = MemPersister()  # authoritative in-RAM image
        self._records_since_compact = 0
        os.makedirs(directory, exist_ok=True)
        with self._lock:
            # sets _records_since_compact to the replayed count
            self._replay_locked()
        self._wal = open(self._wal_path, "ab")
        # a crash-restart loop must not defer compaction forever: if the
        # replayed WAL already exceeds the threshold, compact at boot
        self._maybe_compact()

    @property
    def _wal_path(self) -> str:
        return os.path.join(self._dir, self.WAL)

    @property
    def _snap_path(self) -> str:
        return os.path.join(self._dir, self.SNAPSHOT)

    # recovery --------------------------------------------------------

    def _replay_locked(self) -> None:
        if os.path.exists(self._snap_path):
            with open(self._snap_path, "rb") as f:
                snap = json.loads(f.read().decode("utf-8"))
            for path, hexval in snap.items():
                if hexval is not None:
                    self._mem.set(path, bytes.fromhex(hexval))
                else:
                    # valueless nodes keep the tree shape across restart
                    self._mem.ensure_node(path)
        if not os.path.exists(self._wal_path):
            return
        with open(self._wal_path, "rb") as f:
            data = f.read()
        offset, good = 0, 0
        while offset + _HEADER.size <= len(data):
            length, crc = _HEADER.unpack_from(data, offset)
            start = offset + _HEADER.size
            end = start + length
            if end > len(data):
                break  # torn tail record: crash mid-append
            payload = data[start:end]
            if zlib.crc32(payload) != crc:
                break  # corrupt tail record
            # Replay must be idempotent: a crash inside compact() after
            # the snapshot rename but before the WAL truncation leaves a
            # WAL whose deletes may reference paths the snapshot no
            # longer has.  Deletes of missing paths are no-ops here.
            for op in _decode_txn(payload):
                if isinstance(op, SetOp):
                    self._mem.set(op.path, op.value)
                else:
                    try:
                        self._mem.recursive_delete(op.path)
                    except PersisterError:
                        pass
            self._records_since_compact += 1
            offset, good = end, end
        if good < len(data):
            # truncate the torn tail so future appends are clean
            with open(self._wal_path, "r+b") as f:
                f.truncate(good)

    # write path ------------------------------------------------------

    def _append_locked(self, ops: List[TransactionOp]) -> None:
        payload = _encode_txn(ops)
        self._wal.write(_HEADER.pack(len(payload), zlib.crc32(payload)))
        self._wal.write(payload)
        self._wal.flush()
        if self._fsync:
            os.fsync(self._wal.fileno())
        self._records_since_compact += 1

    def _maybe_compact(self) -> None:
        # called after the RAM image reflects the appended record, so
        # the snapshot never loses the write that triggered compaction
        if self._records_since_compact >= self._compact_every:
            self.compact()

    def compact(self) -> None:
        """Snapshot the tree and truncate the WAL."""
        with self._lock:
            snap = {
                path: (value.hex() if value is not None else None)
                for path, value in self._mem.dump().items()
            }
            tmp = self._snap_path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(json.dumps(snap).encode("utf-8"))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._snap_path)
            if self._fsync:
                # the rename is durable only once the directory entry is
                # on disk; truncating the WAL before that loses every
                # write since the previous snapshot on power failure
                dir_fd = os.open(self._dir, os.O_RDONLY)
                try:
                    os.fsync(dir_fd)
                finally:
                    os.close(dir_fd)
            self._wal.close()
            self._wal = open(self._wal_path, "wb")
            if self._fsync:
                os.fsync(self._wal.fileno())
            self._records_since_compact = 0

    # Persister -------------------------------------------------------

    def get(self, path: str) -> Optional[bytes]:
        with self._lock:
            return self._mem.get(path)

    def set(self, path: str, value: bytes) -> None:
        with self._lock:
            if normalize_path(path) == "/":
                raise PersisterError("cannot store a value at '/'", path)
            self._append_locked([SetOp(path, value)])
            self._mem.set(path, value)
            self._maybe_compact()

    def get_children(self, path: str) -> List[str]:
        with self._lock:
            return self._mem.get_children(path)

    def recursive_delete(self, path: str) -> None:
        with self._lock:
            self._mem.get_children(path)  # raise if absent, before logging
            self._append_locked([DeleteOp(path)])
            self._mem.recursive_delete(path)
            self._maybe_compact()

    def apply(self, ops: Iterable[TransactionOp]) -> None:
        with self._lock:
            ops = list(ops)
            # validate against the RAM image first: WAL must never
            # contain a transaction that fails when applied below
            for op in ops:
                if isinstance(op, DeleteOp) and not self._mem.exists(op.path):
                    raise PersisterError(f"path not found: {op.path}", op.path)
                if isinstance(op, SetOp) and normalize_path(op.path) == "/":
                    raise PersisterError("cannot store a value at '/'", op.path)
            self._append_locked(ops)
            self._mem.apply(ops)
            self._maybe_compact()

    def close(self) -> None:
        with self._lock:
            self._wal.close()


def _encode_txn(ops: List[TransactionOp]) -> bytes:
    encoded = []
    for op in ops:
        if isinstance(op, SetOp):
            encoded.append({"op": "set", "path": op.path, "value": op.value.hex()})
        else:
            encoded.append({"op": "del", "path": op.path})
    return json.dumps(encoded).encode("utf-8")


def _decode_txn(payload: bytes) -> List[TransactionOp]:
    ops: List[TransactionOp] = []
    for entry in json.loads(payload.decode("utf-8")):
        if entry["op"] == "set":
            ops.append(SetOp(entry["path"], bytes.fromhex(entry["value"])))
        else:
            ops.append(DeleteOp(entry["path"]))
    return ops
