"""Storage layer tests (mirrors reference MemPersisterTest/CuratorPersisterTest)."""

import os

import pytest

from dcos_commons_tpu.storage import (
    DeleteOp,
    FileWalPersister,
    MemPersister,
    PersisterCache,
    PersisterError,
    SetOp,
)


def exercise_basic(p):
    p.set("/a/b/c", b"hello")
    assert p.get("/a/b/c") == b"hello"
    assert p.get("/a/b") is None  # implicit parent, no value
    assert p.get_children("/a") == ["b"]
    assert p.get_children("/a/b") == ["c"]
    p.set("/a/b/d", b"world")
    assert p.get_children("/a/b") == ["c", "d"]
    p.recursive_delete("/a/b")
    with pytest.raises(PersisterError):
        p.get("/a/b/c")
    assert p.get_children("/a") == []


def test_mem_persister_basic():
    exercise_basic(MemPersister())


def test_mem_persister_missing_paths():
    p = MemPersister()
    with pytest.raises(PersisterError):
        p.get("/nope")
    with pytest.raises(PersisterError):
        p.get_children("/nope")
    with pytest.raises(PersisterError):
        p.recursive_delete("/nope")
    assert not p.exists("/nope")


def test_mem_persister_transaction():
    p = MemPersister()
    p.set("/x", b"1")
    p.apply([SetOp("/y", b"2"), SetOp("/z", b"3"), DeleteOp("/x")])
    assert p.get("/y") == b"2"
    assert not p.exists("/x")
    # failed transaction leaves no trace
    with pytest.raises(PersisterError):
        p.apply([SetOp("/w", b"4"), DeleteOp("/does-not-exist")])
    assert not p.exists("/w")


def test_mem_persister_clear_all():
    p = MemPersister()
    p.set("/a/b", b"1")
    p.set("/c", b"2")
    p.clear_all_data()
    assert p.get_children_or_empty("/") == []


def test_file_persister_basic(tmp_path):
    exercise_basic(FileWalPersister(str(tmp_path), fsync=False))


def test_file_persister_recovery(tmp_path):
    p = FileWalPersister(str(tmp_path), fsync=False)
    p.set("/tasks/pod-0-server/info", b"info-bytes")
    p.apply([SetOp("/config-target", b"uuid-1"), SetOp("/x", b"y")])
    p.recursive_delete("/x")
    p.close()

    p2 = FileWalPersister(str(tmp_path), fsync=False)
    assert p2.get("/tasks/pod-0-server/info") == b"info-bytes"
    assert p2.get("/config-target") == b"uuid-1"
    assert not p2.exists("/x")
    p2.close()


def test_file_persister_torn_tail(tmp_path):
    """A crash mid-append must not corrupt previously-committed records."""
    p = FileWalPersister(str(tmp_path), fsync=False)
    p.set("/good", b"committed")
    p.close()
    wal = os.path.join(str(tmp_path), FileWalPersister.WAL)
    with open(wal, "ab") as f:
        f.write(b"\xff\xff\xff\xff\x00torn")  # garbage partial record

    p2 = FileWalPersister(str(tmp_path), fsync=False)
    assert p2.get("/good") == b"committed"
    p2.set("/after", b"ok")  # appends cleanly after truncation
    p2.close()
    p3 = FileWalPersister(str(tmp_path), fsync=False)
    assert p3.get("/after") == b"ok"
    p3.close()


def test_file_persister_compaction(tmp_path):
    p = FileWalPersister(str(tmp_path), fsync=False, compact_every=5)
    for i in range(12):
        p.set(f"/k{i}", str(i).encode())
    p.close()
    p2 = FileWalPersister(str(tmp_path), fsync=False)
    for i in range(12):
        assert p2.get(f"/k{i}") == str(i).encode()
    # snapshot exists and WAL was truncated at the last compaction
    assert os.path.exists(os.path.join(str(tmp_path), FileWalPersister.SNAPSHOT))
    p2.close()


def test_persister_cache_write_through(tmp_path):
    backend = FileWalPersister(str(tmp_path), fsync=False)
    cache = PersisterCache(backend)
    cache.set("/a", b"1")
    assert cache.get("/a") == b"1"
    assert backend.get("/a") == b"1"
    cache.close()
    # reload: cache warms from backend
    backend2 = FileWalPersister(str(tmp_path), fsync=False)
    cache2 = PersisterCache(backend2)
    assert cache2.get("/a") == b"1"
    cache2.close()


# -- remote persister (reference: CuratorPersister over ZK) -----------


@pytest.fixture
def state_server():
    from dcos_commons_tpu.storage import StateServer

    server = StateServer().start()
    yield server
    server.stop()


def test_remote_persister_contract(state_server):
    from dcos_commons_tpu.storage import RemotePersister

    exercise_basic(RemotePersister(state_server.url))


def test_remote_persister_binary_values_roundtrip(state_server):
    from dcos_commons_tpu.storage import RemotePersister

    p = RemotePersister(state_server.url)
    blob = bytes(range(256)) * 3
    p.set("/bin", blob)
    assert p.get("/bin") == blob


def test_remote_persister_atomic_apply(state_server):
    from dcos_commons_tpu.storage import RemotePersister

    p = RemotePersister(state_server.url)
    p.set("/t/a", b"1")
    # delete of a missing path fails the WHOLE transaction: /t/a keeps
    # its value, /t/b is never created
    with pytest.raises(PersisterError):
        p.apply([
            SetOp("/t/b", b"2"),
            DeleteOp("/missing"),
        ])
    assert p.get("/t/a") == b"1"
    assert p.get_or_none("/t/b") is None
    p.apply([SetOp("/t/b", b"2"), DeleteOp("/t/a")])
    assert p.get("/t/b") == b"2"
    assert not p.exists("/t/a")


def test_remote_persister_unreachable_raises():
    from dcos_commons_tpu.storage import RemotePersister

    p = RemotePersister("http://127.0.0.1:1", timeout_s=0.5)
    with pytest.raises(PersisterError):
        p.get("/anything")


def test_remote_persister_behind_cache(state_server):
    from dcos_commons_tpu.storage import PersisterCache, RemotePersister

    backend = RemotePersister(state_server.url)
    backend.set("/warm/x", b"pre-existing")
    cache = PersisterCache(backend)
    assert cache.get("/warm/x") == b"pre-existing"
    cache.set("/warm/y", b"through")
    # write-through: a second uncached client sees it
    assert RemotePersister(state_server.url).get("/warm/y") == b"through"


def test_remote_lease_contention_and_expiry(state_server):
    import time

    from dcos_commons_tpu.storage import RemoteLocker

    a = RemoteLocker(state_server.url, "svc", "owner-a", ttl_s=0.6)
    b = RemoteLocker(state_server.url, "svc", "owner-b", ttl_s=0.6)
    assert a.acquire()
    assert not b.acquire()  # held by a
    # a renews faster than expiry: b still locked out after a ttl
    time.sleep(0.9)
    assert not b.acquire()
    # a dies (stop renewing, no release): lease expires, b takes over
    a._stop.set()
    a._thread.join(timeout=2)
    time.sleep(0.9)
    assert b.acquire()
    b.release()


def test_remote_lease_release_frees_immediately(state_server):
    from dcos_commons_tpu.storage import RemoteLocker

    a = RemoteLocker(state_server.url, "svc2", "owner-a", ttl_s=30.0)
    b = RemoteLocker(state_server.url, "svc2", "owner-b", ttl_s=30.0)
    assert a.acquire()
    a.release()
    assert b.acquire()
    b.release()


def test_scheduler_resumes_over_remote_state(state_server):
    """The failover story at sim level: scheduler 1 deploys over the
    remote persister; a fresh scheduler built over the SAME remote
    state resumes without relaunching (reference: scheduler restart
    over ZK, SchedulerRestartServiceTest)."""
    from dcos_commons_tpu.storage import RemotePersister
    from dcos_commons_tpu.testing import (
        AdvanceCycles,
        ExpectDeploymentComplete,
        ExpectLaunchedTasks,
        ExpectNoLaunches,
        SendTaskRunning,
        ServiceTestRunner,
    )

    yaml_text = """
name: remote-svc
pods:
  app:
    count: 1
    tasks:
      main:
        goal: RUNNING
        cmd: "sleep 1000"
        cpus: 0.1
        memory: 32
"""
    runner = ServiceTestRunner(
        yaml_text, persister=RemotePersister(state_server.url)
    )
    runner.run([
        AdvanceCycles(1),
        ExpectLaunchedTasks("app-0-main"),
        SendTaskRunning("app-0-main"),
        ExpectDeploymentComplete(),
    ])
    restarted = runner.restart()
    restarted.run([
        AdvanceCycles(2),
        ExpectNoLaunches(),
        ExpectDeploymentComplete(),
    ])


# -- crash-mid-compaction (reference: crash-consistency of the ZK
#    transaction log; here the snapshot+WAL pair) ---------------------


def test_file_persister_crash_before_snapshot_rename(tmp_path):
    """A crash that leaves a half-written snapshot .tmp behind must not
    lose or corrupt anything: the old snapshot + WAL still hold every
    committed record."""
    d = str(tmp_path / "state")
    p = FileWalPersister(d)
    p.set("/a", b"1")
    p.compact()
    p.set("/b", b"2")
    p.close()
    # simulated torn compaction: garbage .tmp next to the real files
    with open(os.path.join(d, "snapshot.json.tmp"), "wb") as f:
        f.write(b"{not json")
    reopened = FileWalPersister(d)
    assert reopened.get("/a") == b"1"
    assert reopened.get("/b") == b"2"
    reopened.close()


def test_file_persister_crash_after_rename_before_truncate(tmp_path):
    """Crash window between snapshot rename and WAL truncate: the WAL
    still holds records already IN the snapshot; replay over the
    snapshot must be idempotent, including deletes of paths the
    snapshot no longer has."""
    import shutil

    d = str(tmp_path / "state")
    p = FileWalPersister(d)
    p.set("/keep", b"k")
    p.set("/gone", b"g")
    p.recursive_delete("/gone")
    p.set("/keep2", b"k2")
    p.close()
    # preserve the pre-compaction WAL, compact, then restore the old
    # WAL: exactly the on-disk state of a crash after rename
    wal = os.path.join(d, "wal.log")
    saved_wal = str(tmp_path / "saved-wal")
    shutil.copy(wal, saved_wal)
    p = FileWalPersister(d)
    p.compact()
    p.close()
    shutil.copy(saved_wal, wal)
    reopened = FileWalPersister(d)
    assert reopened.get("/keep") == b"k"
    assert reopened.get("/keep2") == b"k2"
    assert not reopened.exists("/gone")
    reopened.close()
