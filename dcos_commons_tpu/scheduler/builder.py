"""SchedulerBuilder: wire persister -> stores -> config update -> plans.

Reference: scheduler/SchedulerBuilder.java:331 (744 LoC): persister +
state/config store wiring, DefaultConfigurationUpdater invocation
(config update validation + target flip), plan selection including
selectDeployPlan's deploy-vs-update choice (:644), namespacing, and
the final DefaultScheduler assembly (DefaultScheduler.java:147).
"""

from __future__ import annotations

import logging
from typing import List, Optional

from dcos_commons_tpu.agent.base import Agent
from dcos_commons_tpu.offer.evaluate import OfferEvaluator
from dcos_commons_tpu.offer.inventory import SliceInventory
from dcos_commons_tpu.offer.ledger import ReservationLedger
from dcos_commons_tpu.plan.backoff import (
    Backoff,
    DisabledBackoff,
    ExponentialBackoff,
)
from dcos_commons_tpu.plan.builders import DeployPlanFactory
from dcos_commons_tpu.plan.generator import PlanGenerator
from dcos_commons_tpu.plan.plan import DEPLOY_PLAN_NAME, UPDATE_PLAN_NAME
from dcos_commons_tpu.plan.plan_manager import DefaultPlanManager
from dcos_commons_tpu.recovery.manager import (
    DefaultRecoveryPlanManager,
    RecoveryPlanOverrider,
)
from dcos_commons_tpu.recovery.monitor import (
    FailureMonitor,
    NeverFailureMonitor,
    TimedFailureMonitor,
)
from dcos_commons_tpu.scheduler.config import SchedulerConfig
from dcos_commons_tpu.scheduler.scheduler import DefaultScheduler
from dcos_commons_tpu.specification.specs import ServiceSpec
from dcos_commons_tpu.specification.validation import (
    ConfigValidationError,
    ValidationContext,
    validate_spec_change,
)
from dcos_commons_tpu.state.config_store import ConfigStore
from dcos_commons_tpu.state.schema import SchemaVersionStore
from dcos_commons_tpu.state.state_store import StateStore
from dcos_commons_tpu.storage import (
    FileWalPersister,
    Persister,
    PersisterCache,
)

LOG = logging.getLogger(__name__)


def make_persister(config: SchedulerConfig) -> Persister:
    """The one place persister selection lives: remote state server
    (behind the full-tree cache) when --state-url is set, else the
    local file WAL (reference: CuratorPersister-vs-local selection in
    SchedulerRunner)."""
    if config.state_url:
        from dcos_commons_tpu.storage.remote import RemotePersister

        persister: Persister = RemotePersister(
            config.state_url,
            auth_token=config.auth_token,
            ca_file=config.tls_ca_file,
        )
        if config.state_cache_enabled:
            persister = PersisterCache(persister)
        return persister
    return FileWalPersister(config.state_dir)


def _apply_autoscale_counts(spec: ServiceSpec, state_store: StateStore):
    """Overlay persisted ``autoscale-count-<pod>`` properties onto the
    target spec's non-gang pod counts.  The property is stamped
    ``count@floor`` with the YAML count it was written against: an
    override only applies while the YAML count is UNCHANGED — the
    moment an operator's config update moves the declared count in
    either direction, the stale autoscale decision is dropped and the
    spec wins (otherwise a scaled-out width would permanently
    neutralize an operator's count decrease).  Applied counts are
    clamped to >= the YAML floor.  Returns
    (spec, {pod_type: yaml_count}) — the baselines the action engine
    scales back down to."""
    import dataclasses

    from dcos_commons_tpu.health.actions import COUNT_PROPERTY_PREFIX

    baselines = {
        pod.type: pod.count for pod in spec.pods if not pod.gang
    }
    new_pods = []
    changed = False
    for pod in spec.pods:
        count = pod.count
        if not pod.gang:
            raw = state_store.fetch_property(
                f"{COUNT_PROPERTY_PREFIX}{pod.type}"
            )
            if raw is not None:
                try:
                    text = raw.decode("utf-8")
                    stored, _sep, floor = text.partition("@")
                    if not floor or int(floor) == pod.count:
                        count = max(pod.count, int(stored))
                except (ValueError, UnicodeDecodeError):
                    count = pod.count
        if count != pod.count:
            pod = dataclasses.replace(pod, count=count)
            changed = True
        new_pods.append(pod)
    if changed:
        spec = dataclasses.replace(spec, pods=tuple(new_pods))
    return spec, baselines


class SchedulerBuilder:
    def __init__(
        self,
        spec: ServiceSpec,
        scheduler_config: Optional[SchedulerConfig] = None,
        persister: Optional[Persister] = None,
    ):
        self._spec = spec
        self._config = scheduler_config or SchedulerConfig()
        self._persister = persister
        self._inventory: Optional[SliceInventory] = None
        self._agent: Optional[Agent] = None
        self._plan_customizer = None
        self._recovery_overriders: List[RecoveryPlanOverrider] = []
        self._failure_monitor: Optional[FailureMonitor] = None
        self._namespace = self._config.service_namespace
        self._secrets_provider = None
        self._leader_lease = None

    # -- fluent wiring (reference: SchedulerBuilder setters) ----------

    def set_inventory(self, inventory: SliceInventory) -> "SchedulerBuilder":
        self._inventory = inventory
        return self

    def set_agent(self, agent: Agent) -> "SchedulerBuilder":
        self._agent = agent
        return self

    def set_plan_customizer(self, customizer) -> "SchedulerBuilder":
        """customizer(plan) -> plan, applied to every built plan
        (reference: PlanCustomizer hook)."""
        self._plan_customizer = customizer
        return self

    def add_recovery_overrider(
        self, overrider: RecoveryPlanOverrider
    ) -> "SchedulerBuilder":
        self._recovery_overriders.append(overrider)
        return self

    def set_failure_monitor(self, monitor: FailureMonitor) -> "SchedulerBuilder":
        self._failure_monitor = monitor
        return self

    def set_secrets_provider(self, provider) -> "SchedulerBuilder":
        """Reference: the SecretsClient the X2 subsystem talks to."""
        self._secrets_provider = provider
        return self

    def set_leader_lease(self, lease) -> "SchedulerBuilder":
        """HA mode (dcos_commons_tpu/ha/): wrap every store mutation
        in the lease-fenced writer, so a deposed leader's writes are
        rejected rather than racing its successor's (reference:
        CuratorLocker's one-scheduler guarantee, upgraded from mutual
        exclusion to fencing)."""
        self._leader_lease = lease
        return self

    # -- build --------------------------------------------------------

    def build(self) -> DefaultScheduler:
        persister = self._persister
        if persister is None:
            persister = make_persister(self._config)
        if self._leader_lease is not None:
            from dcos_commons_tpu.ha.election import FencedPersister

            # every store below is constructed over the fenced writer:
            # no scheduler-path mutation can bypass the lease check.
            # Reuse an already-fenced persister (the HA runner fences
            # its own handle) so rejection counters stay in one place.
            if not (isinstance(persister, FencedPersister)
                    and persister.lease is self._leader_lease):
                persister = FencedPersister(persister, self._leader_lease)
        SchemaVersionStore(persister).check()
        state_store = StateStore(persister, self._namespace)
        config_store = ConfigStore(persister, self._namespace)
        ledger = ReservationLedger(persister, self._namespace)

        if self._config.uninstall:
            # SDK_UNINSTALL set: tear down instead of deploying
            # (reference: SchedulerBuilder.build returning
            # UninstallScheduler).  Over already-wiped state every
            # phase is trivially complete = the skeleton scheduler.
            from dcos_commons_tpu.state.framework_store import FrameworkStore
            from dcos_commons_tpu.uninstall import UninstallScheduler

            inventory = self._inventory or SliceInventory()
            agent = self._agent
            if agent is None:
                from dcos_commons_tpu.agent.local import LocalProcessAgent

                agent = LocalProcessAgent(self._config.sandbox_root)
            return UninstallScheduler(
                spec=self._spec,
                state_store=state_store,
                ledger=ledger,
                inventory=inventory,
                agent=agent,
                persister=persister,
                config_store=config_store,
                framework_store=FrameworkStore(persister),
            )

        target_id, config_errors = self._update_configuration(
            state_store, config_store
        )
        target_spec = self._load_target_spec(config_store, target_id)
        # the autoscale desired-count overrides (ISSUE 15): a prior
        # incarnation's set_pod_count verb persisted the scaled width;
        # applying it BEFORE plan construction means the deploy plan
        # covers the scaled instances (seeding COMPLETE from state)
        # and the decommission factory sees a mid-scale-in victim as
        # surplus.  The YAML counts stay recorded as the scale-in
        # floor (engine baselines).
        target_spec, autoscale_baselines = _apply_autoscale_counts(
            target_spec, state_store
        )

        backoff = self._make_backoff()
        factory = DeployPlanFactory(backoff)
        generator = PlanGenerator(backoff)
        plans_raw = target_spec.plans or {}
        has_completed = state_store.deployment_was_completed()
        plan_name = UPDATE_PLAN_NAME if has_completed else DEPLOY_PLAN_NAME
        raw_deploy = plans_raw.get("deploy")
        raw_update = plans_raw.get("update")
        if has_completed and raw_update:
            # a custom update plan replaces the deploy plan once the
            # initial deployment has completed (reference:
            # SchedulerBuilder.selectDeployPlan, SchedulerBuilder.java:644)
            deploy_plan = generator.generate(
                target_spec, UPDATE_PLAN_NAME, raw_update, state_store,
                target_id,
            )
        elif raw_deploy:
            deploy_plan = generator.generate(
                target_spec, plan_name, raw_deploy, state_store, target_id
            )
        else:
            deploy_plan = factory.build(
                target_spec, state_store, target_id, plan_name
            )
        deploy_plan.errors.extend(config_errors)
        if self._plan_customizer is not None:
            deploy_plan = self._plan_customizer(deploy_plan) or deploy_plan
        deploy_manager = DefaultPlanManager(deploy_plan)

        monitor = self._failure_monitor
        if monitor is None:
            policy = target_spec.replacement_failure_policy
            if policy is not None:
                monitor = TimedFailureMonitor(policy.permanent_failure_timeout_s)
            else:
                monitor = NeverFailureMonitor()

        def externally_managed(asset: str) -> bool:
            for step in deploy_plan.all_steps():
                if asset in step.get_asset_names() and not step.is_complete:
                    return True
            return False

        recovery_manager = DefaultRecoveryPlanManager(
            target_spec,
            state_store,
            failure_monitor=monitor,
            backoff=backoff,
            overriders=self._recovery_overriders,
            externally_managed=externally_managed,
        )

        evaluator = OfferEvaluator(
            state_store, ledger, target_spec.name, target_id
        )
        inventory = self._inventory or SliceInventory()
        # gang recovery's elastic step probes maintenance windows
        # through the shared inventory (wait-for-window beats shrink)
        recovery_manager.inventory = inventory
        agent = self._agent
        if agent is None:
            from dcos_commons_tpu.agent.local import LocalProcessAgent

            agent = LocalProcessAgent(self._config.sandbox_root)

        # scale-down: stored pod instances the target spec no longer
        # covers get a decommission plan (kill -> unreserve -> erase)
        from dcos_commons_tpu.decommission import DecommissionPlanFactory

        other_managers: List = []
        # custom YAML plans (sidecar: backup/restore/repair...) are
        # built interrupted and kicked off by `plan start` (reference:
        # SchedulerBuilder.java:155 createInterrupted; cassandra's
        # backup plans are the canonical consumer)
        for custom_name, raw_custom in plans_raw.items():
            if custom_name in ("deploy", "update") or not raw_custom:
                continue
            custom_plan = generator.generate(
                target_spec, custom_name, raw_custom, state_store, target_id
            )
            custom_plan.interrupt()
            if self._plan_customizer is not None:
                custom_plan = self._plan_customizer(custom_plan) or custom_plan
            other_managers.append(DefaultPlanManager(custom_plan))
        # the durable event journal is created HERE (and handed to the
        # health monitor below) because the decommission scan needs
        # it: an in-flight scale-in latched in the journal owns its
        # victim's teardown — the re-synthesized scale-in phase tears
        # down through the router drain grace, while this plan's kill
        # step has no drain.  Excluding the victim keeps the failover
        # path honoring the full grace instead of racing past it.
        from dcos_commons_tpu.health import (
            EventJournal,
            StatePropertyBackend,
        )
        from dcos_commons_tpu.health.actions import seed_latches
        from dcos_commons_tpu.specification.specs import (
            pod_instance_name,
        )

        health_journal = None
        scale_in_victims: set = set()
        if self._config.health_enabled and \
                self._config.health_journal_capacity > 0:
            health_journal = EventJournal(
                StatePropertyBackend(state_store),
                capacity=self._config.health_journal_capacity,
            )
            in_flight, _done, _replace = seed_latches(
                health_journal.events(kinds=("health",))
            )
            scale_in_victims = {
                pod_instance_name(pod_type, latch["from"] - 1)
                for pod_type, latch in in_flight.items()
                if latch["direction"] == "in"
            }
        decommission_plan = DecommissionPlanFactory().build(
            target_spec, state_store, exclude=scale_in_victims
        )
        if decommission_plan.phases:
            if self._plan_customizer is not None:
                decommission_plan = self._plan_customizer(
                    decommission_plan
                ) or decommission_plan
            other_managers.append(DefaultPlanManager(decommission_plan))

        # security plane: a secrets provider must exist BEFORE a spec
        # that references secrets may deploy (reference: the
        # TLSRequiresServiceAccount gating pattern — fail configuration,
        # not the eventual launch); TLS just needs a persisted CA
        secrets_provider = self._secrets_provider
        if secrets_provider is None and self._config.secrets_dir:
            from dcos_commons_tpu.security import FileSecretsProvider

            secrets_provider = FileSecretsProvider(self._config.secrets_dir)
        uses_secrets = any(pod.secrets for pod in target_spec.pods)
        if uses_secrets and secrets_provider is None:
            raise ConfigValidationError([
                "service references secrets but no secrets provider is "
                "configured (set SECRETS_DIR / --secrets-dir or wire one "
                "via SchedulerBuilder.set_secrets_provider)"
            ])
        certificate_authority = None
        if any(
            task.transport_encryption
            for pod in target_spec.pods
            for task in pod.tasks
        ):
            from dcos_commons_tpu.security import CertificateAuthority

            certificate_authority = CertificateAuthority.load_or_create(
                persister
            )

        from dcos_commons_tpu.state.framework_store import FrameworkStore

        from dcos_commons_tpu.runtime.token_bucket import TokenBucket

        from dcos_commons_tpu.trace.recorder import TraceRecorder

        # health plane: config-driven monitor (journal capacity 0 =
        # the whole plane off).  The journal persists through
        # state_store, i.e. the (possibly lease-fenced) wired
        # persister — a deposed leader's flush is rejected, the
        # successor replays the journal and resumes the seq.
        from dcos_commons_tpu.health import (
            HealthMonitor,
            ServingSloWatcher,
            StragglerDetector,
        )
        from dcos_commons_tpu.health.monitor import NullHealthMonitor

        if health_journal is not None:
            health_monitor = HealthMonitor(
                journal=health_journal,
                straggler=StragglerDetector(
                    threshold=self._config.health_straggler_ratio,
                    window=self._config.health_straggler_window,
                ),
                slo=ServingSloWatcher(
                    ttft_p95_slo_s=self._config.health_ttft_p95_slo_s,
                    queue_depth_slo=self._config.health_queue_depth_slo,
                    kv_occupancy_slo=self._config.health_kv_occupancy_slo,
                    kv_pages_free_slo=(
                        self._config.health_kv_pages_free_slo
                    ),
                ),
                telemetry_interval_s=(
                    self._config.health_telemetry_interval_s
                ),
                history_interval_s=self._config.health_history_interval_s,
                auto_replace=self._config.health_auto_replace,
                quiet_factor=self._config.autoscale_quiet_factor,
            )
        else:
            health_monitor = NullHealthMonitor()

        from dcos_commons_tpu.health.actions import ActionPolicy

        action_policy = ActionPolicy(
            autoscale=self._config.health_autoscale,
            remediation=self._config.health_remediation,
            max_instances=self._config.autoscale_max_instances,
            breach_hold_s=self._config.autoscale_breach_hold_s,
            quiet_hold_s=self._config.autoscale_quiet_hold_s,
            quiet_factor=self._config.autoscale_quiet_factor,
            cooldown_out_s=self._config.autoscale_cooldown_out_s,
            cooldown_in_s=self._config.autoscale_cooldown_in_s,
            drain_grace_s=self._config.autoscale_drain_grace_s,
        )

        scheduler = DefaultScheduler(
            spec=target_spec,
            state_store=state_store,
            ledger=ledger,
            inventory=inventory,
            agent=agent,
            evaluator=evaluator,
            deploy_manager=deploy_manager,
            recovery_manager=recovery_manager,
            other_managers=other_managers,
            config_store=config_store,
            framework_store=FrameworkStore(persister),
            revive_bucket=TokenBucket(
                capacity=self._config.revive_capacity,
                refill_interval_s=self._config.revive_refill_s,
            ),
            tracer=TraceRecorder(
                capacity=self._config.trace_capacity,
                service=target_spec.name,
            ),
            health_monitor=health_monitor,
            action_policy=action_policy,
        )
        # the YAML instance counts are the scale-in floor; recorded
        # here because the live spec may already carry a scaled width
        scheduler.actions.baselines.update(autoscale_baselines)
        # scale-out deployment steps back off like deploy-plan steps
        # (a crash-looping scaled instance must not hot-retry)
        scheduler.actions.backoff = backoff
        scheduler.secrets_provider = secrets_provider
        scheduler.certificate_authority = certificate_authority
        if self._leader_lease is not None:
            from dcos_commons_tpu.ha.election import HAState

            HAState(
                persister, self._leader_lease.name,
                lease=self._leader_lease,
            ).attach(scheduler)
        return scheduler

    # -- config update (reference: DefaultConfigurationUpdater:159) ---

    def _update_configuration(self, state_store, config_store):
        errors: List[str] = []
        old_target_id = config_store.get_target_config()
        old_spec = None
        if old_target_id:
            old_dict = config_store.fetch(old_target_id)
            if old_dict is not None:
                old_spec = ServiceSpec.from_dict(old_dict)
        if old_spec is not None and old_spec == self._spec:
            return old_target_id, errors
        context = ValidationContext(
            deployment_completed=state_store.deployment_was_completed(),
            secrets_provider_present=(
                self._secrets_provider is not None
                or bool(self._config.secrets_dir)
            ),
            # only meaningful when launches cross a network: a local
            # agent writes cert material straight to disk, so TLS
            # without a token is fine there (None = skip the check)
            auth_token_present=(
                bool(self._config.auth_token)
                if getattr(self._agent, "is_remote", False) else None
            ),
        )
        try:
            validate_spec_change(old_spec, self._spec, context=context)
        except ConfigValidationError as e:
            errors.extend(e.errors)
            if old_target_id is not None:
                LOG.error(
                    "config update rejected, keeping target %s: %s",
                    old_target_id, e.errors,
                )
                return old_target_id, errors
            raise  # invalid initial config: refuse to start
        new_id = config_store.store(self._spec.to_dict())
        config_store.set_target_config(new_id)
        if old_spec is not None:
            # a fresh rollout begins: the update plan must redeploy
            # changed pods, tracked against the new target id
            LOG.info("target config %s -> %s", old_target_id, new_id)
            referenced = set()
            for info in state_store.fetch_tasks():
                cfg = info.labels.get("target_configuration")
                if cfg:
                    referenced.add(cfg)
            config_store.prune(list(referenced))
        return new_id, errors

    def _load_target_spec(self, config_store, target_id) -> ServiceSpec:
        data = config_store.fetch(target_id)
        return ServiceSpec.from_dict(data) if data else self._spec

    def _make_backoff(self) -> Backoff:
        if not self._config.backoff_enabled:
            return DisabledBackoff()
        return ExponentialBackoff(
            initial_s=self._config.backoff_initial_s,
            factor=self._config.backoff_factor,
            max_s=self._config.backoff_max_s,
        )
