"""Feature-matrix remainder (VERDICT r3 #7): discovery/custom-TLD
naming, enable-disable boolean sections, non-recoverable plan-ERROR
surfacing, web-url advertisement, /v1/state/files, and the
TLS-requires-credentials validator.

Reference: frameworks/helloworld/src/main/dist/{discovery,custom_tld,
enable-disable,non_recoverable_state,web-url}.yml and their tests;
http/queries/StateQueries.java:78; config/validate/
TLSRequiresServiceAccount.java.
"""

import base64
import json
import os

import pytest

from dcos_commons_tpu.common import TaskState
from dcos_commons_tpu.plan.status import Status
from dcos_commons_tpu.specification import from_yaml
from dcos_commons_tpu.specification.yaml_spec import render_template
from dcos_commons_tpu.testing import (
    AdvanceCycles,
    ExpectDeploymentComplete,
    ExpectLaunchedTasks,
    ExpectPlanStatus,
    SendTaskRunning,
    ServiceTestRunner,
)

HELLOWORLD = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "frameworks",
    "helloworld",
)


def load(yaml_name: str) -> str:
    with open(os.path.join(HELLOWORLD, yaml_name), encoding="utf-8") as f:
        return f.read()


# -- mustache boolean sections (enable-disable plane) -----------------


def test_render_template_boolean_sections():
    text = "a\n{{#FLAG}}\non\n{{/FLAG}}\n{{^FLAG}}\noff\n{{/FLAG}}\nz\n"
    assert render_template(text, {"FLAG": "true"}) == "a\non\nz\n"
    assert render_template(text, {"FLAG": "false"}) == "a\noff\nz\n"
    assert render_template(text, {}) == "a\noff\nz\n"
    # falsy spellings
    for falsy in ("", "0", "no", "False"):
        assert "off" in render_template(text, {"FLAG": falsy})
    # vars inside a hidden block are never "missing"
    hidden = "{{#FLAG}}{{UNDEFINED_VAR}}{{/FLAG}}ok"
    assert render_template(hidden, {}) == "ok"


def test_render_template_unbalanced_sections_fail_loudly():
    """A {{#VAR}} with a missing/mistyped closer never matches the
    section regex and would pass through SILENTLY into the rendered
    YAML (advisor r4) — it must fail like missing variables do."""
    import pytest

    from dcos_commons_tpu.specification.specs import SpecError

    for bad in (
        "a\n{{#FLAG}}\non\nz\n",          # no closer
        "a\n{{#FLAG}}\non\n{{/FLGA}}\n",  # mistyped closer
        "a\non\n{{/FLAG}}\nz\n",          # stray closer
        "{{^FLAG}}off",                   # inverted, no closer
        "{{#MY-FLAG}}x{{/MY-FLAG}}",      # hyphen: not section grammar
        "{{# FLAG}}x{{/FLAG}}",           # stray space in the tag
    ):
        with pytest.raises(SpecError, match="section tags"):
            render_template(bad, {"FLAG": "true"})
    # balanced nesting still renders fine
    nested = "{{#A}}x{{#B}}y{{/B}}z{{/A}}"
    assert render_template(nested, {"A": "1", "B": "1"}) == "xyz"


def test_enable_disable_yaml_flips_task_set():
    """TEST_BOOLEAN=false deploys only server-b; true deploys both
    (reference: test_enable_disable.py flows)."""
    spec_off = from_yaml(load("enable-disable.yml"),
                         env={"TEST_BOOLEAN": "false"})
    steps_off = json.dumps(spec_off.plans)
    assert "server-a" not in steps_off
    runner = ServiceTestRunner(
        load("enable-disable.yml"),
        env={"TEST_BOOLEAN": "false", "HELLO_COUNT": "1"},
    )
    runner.run([
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-0-server-b"),
        SendTaskRunning("hello-0-server-b"),
        ExpectDeploymentComplete(),
    ])
    assert runner.world.agent.task_id_of("hello-0-server-a") is None

    enabled = ServiceTestRunner(
        load("enable-disable.yml"),
        env={"TEST_BOOLEAN": "true", "HELLO_COUNT": "1"},
    )
    enabled.run([
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-0-server-a"),
        SendTaskRunning("hello-0-server-a"),
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-0-server-b"),
        SendTaskRunning("hello-0-server-b"),
        ExpectDeploymentComplete(),
    ])


# -- non-recoverable plan-ERROR surfacing -----------------------------


def test_task_error_surfaces_as_plan_error_and_restart_clears():
    """A TASK_ERROR (provisioning can never succeed) parks the step at
    ERROR instead of crash-looping; `plan restart` clears it
    (reference: non_recoverable_state.yml + fast-failure semantics)."""
    from dcos_commons_tpu.common import TaskStatus

    runner = ServiceTestRunner(load("simple.yml"))
    runner.run([AdvanceCycles(1), ExpectLaunchedTasks("hello-0-server")])
    agent = runner.world.agent
    task_id = agent.task_id_of("hello-0-server")
    agent.send(TaskStatus(
        task_id=task_id, state=TaskState.ERROR,
        message="config template render failed: no such template",
    ))
    runner.run([
        AdvanceCycles(2),
        ExpectPlanStatus("deploy", Status.ERROR),
    ])
    from dcos_commons_tpu.http.api import SchedulerApi

    plan = runner.world.scheduler.plans()["deploy"]
    code, body = SchedulerApi(runner.world.scheduler).get_plan("deploy")
    assert code in (200, 202, 503)
    assert "no such template" in json.dumps(body)
    # no relaunch while parked at ERROR (not a crash loop)
    before = len(agent.launched)
    runner.run([AdvanceCycles(3)])
    assert len(agent.launched) == before
    # operator exit: restart the plan -> step re-runs
    for phase in plan.phases:
        for step in phase.steps:
            step.restart()
    runner.run([AdvanceCycles(1)])
    assert len(agent.launched) == before + 1


@pytest.mark.slow
def test_e2e_missing_template_is_plan_error(tmp_path):
    """non-recoverable.yml through a REAL agent: the missing template
    ERRORs the launch and the deploy plan shows ERROR over HTTP."""
    from dcos_commons_tpu.testing.integration import (
        AgentProcess,
        SchedulerProcess,
        reap_orphan_tasks,
        wait_for,
    )

    repo = os.path.dirname(HELLOWORLD.rstrip(os.sep))
    repo = os.path.dirname(repo)
    agents = [AgentProcess("h0", str(tmp_path / "agent-0"), repo)]
    sched = None
    try:
        topology = tmp_path / "topology.yml"
        topology.write_text(
            "hosts:\n  - host_id: h0\n"
            f"    agent_url: {agents[0].url}\n"
            "    cpus: 4.0\n    memory_mb: 8192\n"
        )
        sched = SchedulerProcess(
            os.path.join(HELLOWORLD, "non-recoverable.yml"),
            str(topology), str(tmp_path / "sched"),
            env={"ENABLE_BACKOFF": "false"}, repo_root=repo,
        )
        client = sched.client()
        wait_for(
            lambda: client.plan_status("deploy") == "ERROR" or None,
            timeout_s=60, what="deploy plan ERROR",
        )
        body = client.get("/v1/plans/deploy")
        assert body["status"] == "ERROR"
        assert any("template" in e for e in body.get("errors", [])), body
    finally:
        if sched is not None:
            sched.terminate()
        reap_orphan_tasks(agents)
        for agent in agents:
            agent.stop()


# -- discovery / custom TLD / web-url ---------------------------------


def _endpoint(runner, name):
    from dcos_commons_tpu.http.api import SchedulerApi

    api = SchedulerApi(runner.world.scheduler)
    code, body = api.get_endpoint(name)
    assert code == 200, body
    return body["address"]


def test_discovery_prefix_names_endpoints():
    runner = ServiceTestRunner(load("discovery.yml"))
    runner.run([
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-0-server"),
        SendTaskRunning("hello-0-server"),
        ExpectDeploymentComplete(),
    ])
    dns = _endpoint(runner, "dns")
    assert any(
        entry.startswith("hello-0.helloworld.fleet.local:")
        for entry in dns
    ), dns


def test_custom_tld_renames_dns_suffix():
    runner = ServiceTestRunner(
        load("custom-tld.yml"), env={"SERVICE_TLD": "corp.internal"}
    )
    runner.run([
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-0-server"),
        SendTaskRunning("hello-0-server"),
        ExpectDeploymentComplete(),
    ])
    dns = _endpoint(runner, "dns")
    assert any(
        entry.startswith("hello-0.helloworld.corp.internal:")
        for entry in dns
    ), dns


def test_web_url_advertised_under_web_endpoint():
    runner = ServiceTestRunner(
        load("web-url.yml"), env={"WEB_URL": "http://ui.example:9090"}
    )
    runner.run([
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-0-server"),
        SendTaskRunning("hello-0-server"),
        ExpectDeploymentComplete(),
    ])
    assert _endpoint(runner, "web") == ["http://ui.example:9090"]
    assert runner.spec.web_url == "http://ui.example:9090"


def test_dash_named_tasks_keep_their_endpoints():
    """Task names containing dashes (server-a) must still resolve to
    their spec for ports/vip/dns listing (prefix-strip, not
    last-dash split)."""
    yaml_text = """
name: dashed
pods:
  hello:
    count: 1
    tasks:
      server-a:
        goal: RUNNING
        cmd: "sleep 100"
        cpus: 0.1
        memory: 32
        discovery:
          prefix: hello
        ports:
          rpc:
            port: 0
"""
    runner = ServiceTestRunner(yaml_text)
    runner.run([
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-0-server-a"),
        SendTaskRunning("hello-0-server-a"),
        ExpectDeploymentComplete(),
    ])
    assert any(
        e.startswith("hello-0.dashed.fleet.local:")
        for e in _endpoint(runner, "dns")
    )
    assert _endpoint(runner, "rpc")  # the port listing survives too


def test_gang_step_accumulates_multiple_task_errors():
    """Two distinct provisioning errors in one step both surface —
    fixing one must not hide the other for a whole rollout."""
    from dcos_commons_tpu.common import TaskStatus

    yaml_text = """
name: multi
pods:
  app:
    count: 1
    tasks:
      alpha:
        goal: RUNNING
        cmd: "sleep 100"
        cpus: 0.1
        memory: 32
      beta:
        goal: RUNNING
        cmd: "sleep 100"
        cpus: 0.1
        memory: 32
"""
    runner = ServiceTestRunner(yaml_text)
    runner.run([AdvanceCycles(1)])
    agent = runner.world.agent
    for task, message in (
        ("app-0-alpha", "missing template"),
        ("app-0-beta", "bad secret"),
    ):
        agent.send(TaskStatus(
            task_id=agent.task_id_of(task),
            state=TaskState.ERROR, message=message,
        ))
    runner.run([AdvanceCycles(1)])
    from dcos_commons_tpu.http.api import SchedulerApi

    _code, body = SchedulerApi(runner.world.scheduler).get_plan("deploy")
    flat = json.dumps(body)
    assert "missing template" in flat and "bad secret" in flat


# -- /v1/state/files --------------------------------------------------


def test_state_files_roundtrip():
    from dcos_commons_tpu.http.api import SchedulerApi

    runner = ServiceTestRunner(load("simple.yml"))
    runner.run([AdvanceCycles(1)])
    api = SchedulerApi(runner.world.scheduler)
    assert api.state_files() == (200, [])
    payload = base64.b64encode(b"keytab-bytes").decode()
    code, body = api.state_file_put("svc.keytab", {"content": payload})
    assert code == 200 and body["bytes"] == 12
    assert api.state_files() == (200, ["svc.keytab"])
    code, body = api.state_file_get("svc.keytab")
    assert code == 200
    assert base64.b64decode(body["content"]) == b"keytab-bytes"
    # bad requests are pointed
    assert api.state_file_put("x", {})[0] == 400
    assert api.state_file_put("x", {"content": "!!!"})[0] == 400
    big = base64.b64encode(b"x" * ((1 << 20) + 1)).decode()
    assert api.state_file_put("x", {"content": big})[0] == 413
    assert api.state_file_get("missing")[0] == 404


# -- TLS requires credentials validator -------------------------------


def test_tls_requires_credentials_validator():
    from dcos_commons_tpu.specification.validation import (
        ValidationContext,
        tls_requires_credentials,
    )

    spec = from_yaml(load("tls.yml"))
    # remote agents, no token: rejected
    errs = tls_requires_credentials(
        None, spec, ValidationContext(auth_token_present=False)
    )
    assert errs and "transport-encryption" in errs[0]
    # token present, or local agents (None = not applicable): clean
    assert tls_requires_credentials(
        None, spec, ValidationContext(auth_token_present=True)
    ) == []
    assert tls_requires_credentials(
        None, spec, ValidationContext(auth_token_present=None)
    ) == []
    # a spec without TLS never triggers it
    plain = from_yaml(load("simple.yml"))
    assert tls_requires_credentials(
        None, plain, ValidationContext(auth_token_present=False)
    ) == []
