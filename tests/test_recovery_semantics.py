"""Essential-task scoping + min-replace-delay recovery semantics.

Reference: TaskSpec.isEssential (a non-essential task's death must not
restart its healthy siblings) and ReplacementFailurePolicy's
minReplaceDelay (successive PERMANENT replaces of one pod instance are
rate limited) — both parsed since round 1, now enforced.
"""

from dcos_commons_tpu.plan.step import RecoveryType
from dcos_commons_tpu.recovery.monitor import TestingFailureMonitor
from dcos_commons_tpu.testing import (
    AdvanceCycles,
    ExpectDeploymentComplete,
    ExpectLaunchedTasks,
    ExpectTaskNotKilled,
    SendTaskFailed,
    SendTaskRunning,
    ServiceTestRunner,
)

MIXED_YAML = """
name: mixed
pods:
  app:
    count: 1
    tasks:
      server:
        goal: RUNNING
        cmd: "serve"
        cpus: 0.2
        memory: 64
      metrics:
        goal: RUNNING
        cmd: "scrape"
        cpus: 0.1
        memory: 32
        essential: false
"""


def deploy(runner):
    runner.run([
        AdvanceCycles(1),
        ExpectLaunchedTasks("app-0-server", "app-0-metrics"),
        SendTaskRunning("app-0-server"),
        SendTaskRunning("app-0-metrics"),
        ExpectDeploymentComplete(),
    ])


def test_nonessential_failure_recovers_alone():
    runner = ServiceTestRunner(MIXED_YAML)
    deploy(runner)
    server_launches = len(runner.world.agent.launches_of("app-0-server"))
    runner.run([
        SendTaskFailed("app-0-metrics"),
        AdvanceCycles(1),
        ExpectLaunchedTasks("app-0-metrics"),
        ExpectTaskNotKilled("app-0-server"),
        SendTaskRunning("app-0-metrics"),
    ])
    # the essential sibling was never touched
    assert len(runner.world.agent.launches_of("app-0-server")) == \
        server_launches


def test_essential_failure_recovers_whole_pod():
    runner = ServiceTestRunner(MIXED_YAML)
    deploy(runner)
    runner.run([
        SendTaskFailed("app-0-server"),
        AdvanceCycles(1),
    ])
    recovery = runner.world.scheduler.plan("recovery")
    steps = [s for p in recovery.phases for s in p.steps]
    assert steps, "no recovery step synthesized"
    # the requirement spans BOTH tasks: the pod restarts as a unit
    assert set(steps[0].requirement.task_names()) == {
        "app-0-server", "app-0-metrics"
    }


DELAY_YAML = """
name: delayed
replacement-failure-policy:
  permanent-failure-timeout-secs: 0
  min-replace-delay-secs: 3600
pods:
  app:
    count: 1
    tasks:
      main:
        goal: RUNNING
        cmd: "serve"
        cpus: 0.1
        memory: 32
"""


def test_min_replace_delay_rate_limits_permanent():
    """The monitor demands PERMANENT every failure, but within the
    min-replace window the second failure stays TRANSIENT."""
    runner = ServiceTestRunner(
        DELAY_YAML,
        builder_hook=lambda b: b.set_failure_monitor(
            TestingFailureMonitor(permanent_tasks=["app-0-main"])
        ),
    )
    runner.run([
        AdvanceCycles(1),
        SendTaskRunning("app-0-main"),
        ExpectDeploymentComplete(),
        SendTaskFailed("app-0-main"),
        AdvanceCycles(1),
    ])
    scheduler = runner.world.scheduler

    def recovery_types():
        return [
            s.requirement.recovery_type
            for p in scheduler.plan("recovery").phases
            for s in p.steps
            if hasattr(s, "requirement")
        ]

    assert recovery_types() == [RecoveryType.PERMANENT]
    runner.run([
        SendTaskRunning("app-0-main"),
        AdvanceCycles(1),
        # fail again immediately: inside the 3600s window the monitor's
        # PERMANENT verdict is held back to TRANSIENT
        SendTaskFailed("app-0-main"),
        AdvanceCycles(1),
    ])
    assert recovery_types() == [RecoveryType.TRANSIENT]


def test_nonessential_permanent_escalates_to_whole_pod():
    """A non-essential task escalated to PERMANENT must take the whole
    pod: a subset re-placed from scratch would split colocation."""
    runner = ServiceTestRunner(
        MIXED_YAML,
        builder_hook=lambda b: b.set_failure_monitor(
            TestingFailureMonitor(permanent_tasks=["app-0-metrics"])
        ),
    )
    deploy(runner)
    runner.run([
        SendTaskFailed("app-0-metrics"),
        AdvanceCycles(1),
    ])
    recovery = runner.world.scheduler.plan("recovery")
    steps = [s for p in recovery.phases for s in p.steps]
    assert steps[0].requirement.recovery_type is RecoveryType.PERMANENT
    assert set(steps[0].requirement.task_names()) == {
        "app-0-server", "app-0-metrics"
    }


def test_essential_failure_widens_inflight_subset_phase():
    """An essential task dying while a non-essential subset phase is
    in flight must not be deferred behind it."""
    runner = ServiceTestRunner(MIXED_YAML)
    deploy(runner)
    runner.run([
        SendTaskFailed("app-0-metrics"),
        AdvanceCycles(1),
        ExpectLaunchedTasks("app-0-metrics"),
        # before metrics recovers, the essential server dies too
        SendTaskFailed("app-0-server"),
        AdvanceCycles(1),
    ])
    recovery = runner.world.scheduler.plan("recovery")
    steps = [s for p in recovery.phases for s in p.steps]
    assert set().union(*(
        set(s.requirement.task_names()) for s in steps
    )) == {"app-0-server", "app-0-metrics"}


def test_gang_replace_delay_covers_every_worker():
    """A gang PERMANENT replace stamps EVERY instance, so a follow-up
    failure seen on a different worker is still rate limited."""
    gang_yaml = """
name: gangd
replacement-failure-policy:
  permanent-failure-timeout-secs: 0
  min-replace-delay-secs: 3600
pods:
  worker:
    count: 2
    gang: true
    tasks:
      main:
        goal: RUNNING
        cmd: "train"
        cpus: 0.1
        memory: 32
"""
    from dcos_commons_tpu.offer.inventory import TpuHost

    runner = ServiceTestRunner(
        gang_yaml,
        hosts=[TpuHost(host_id=f"h{i}") for i in range(3)],
        builder_hook=lambda b: b.set_failure_monitor(
            TestingFailureMonitor(
                permanent_tasks=["worker-0-main", "worker-1-main"]
            )
        ),
    )
    runner.run([
        AdvanceCycles(1),
        SendTaskRunning("worker-0-main"),
        SendTaskRunning("worker-1-main"),
        ExpectDeploymentComplete(),
        SendTaskFailed("worker-0-main"),
        AdvanceCycles(1),
    ])
    scheduler = runner.world.scheduler

    def recovery_types():
        return [
            s.requirement.recovery_type
            for p in scheduler.plan("recovery").phases
            for s in p.steps
            if hasattr(s, "requirement")
        ]

    assert recovery_types() == [RecoveryType.PERMANENT]
    # PERMANENT gang recovery is now the plan-driven choreography
    # (ISSUE 13): kill-survivors (worker-1's auto-acked KILLED lands
    # on the next intake), unreserve-slice, then the replace step
    # re-launches the whole gang under fresh task ids
    runner.run([
        AdvanceCycles(4),
        SendTaskRunning("worker-0-main"),
        SendTaskRunning("worker-1-main"),
        AdvanceCycles(2),
    ])
    assert runner.world.scheduler.plan("recovery").is_complete
    runner.run([
        # the OTHER worker fails inside the window: still rate limited
        SendTaskFailed("worker-1-main"),
        AdvanceCycles(1),
    ])
    assert recovery_types() == [RecoveryType.TRANSIENT]
