"""Collective and roofline benchmarks: the ICI north-star measurement.

BASELINE.json's second metric is pjit allreduce GB/s/chip at >80% of
ICI line rate on a multi-host slice.  This module measures it the
XLA-native way: a shard_map program per collective (psum, all_gather,
reduce_scatter, ppermute ring hop), iterated inside one compiled
lax.scan so dispatch overhead never touches the clock, timed end to
end, and converted to the standard algorithmic-bandwidth model
(ring allreduce moves 2(n-1)/n bytes per byte of payload per chip).

On a single chip the collectives degenerate, so the same module also
measures the chip rooflines the multi-chip numbers will sit under:
HBM copy bandwidth and bf16 matmul TFLOPs.

Reference analogue: none — the reference's "distributed communication
backend" is the Mesos scheduler API + ZooKeeper (SURVEY.md §5.8); the
data-plane bandwidth axis is green-field TPU work.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from dcos_commons_tpu.parallel.compat import axis_size


def _sync(x) -> float:
    """Force completion INCLUDING a device->host readback.

    On the axon relay platform block_until_ready can return before the
    computation has finished; fetching a scalar that depends on the
    result is the only reliable fence (same workaround as bench.py's
    train-step timing)."""
    jax.block_until_ready(x)
    return float(jax.device_get(jnp.sum(x.astype(jnp.float32))))

# bytes moved over ICI per chip, per byte of the PER-CHIP shard S, on
# an n-ring (NCCL bus-bandwidth convention): allreduce carries S both
# ways in n-1 chunked steps (2(n-1)/n * S since the reduce+broadcast
# halves each move S/n per step over 2(n-1) steps... net 2(n-1)/n*S);
# all_gather forwards n-1 shard-sized chunks ((n-1)*S); tiled
# reduce_scatter reduces an n*S input down to S, also (n-1)*S per
# chip; a ring ppermute moves exactly S.
_ALGO_FACTOR = {
    "psum": lambda n: 2.0 * (n - 1) / n,
    "all_gather": lambda n: float(n - 1),
    "reduce_scatter": lambda n: float(n - 1),
    "ppermute": lambda n: 1.0,
}


def _bench_fn(collective: str, axis: str, iters: int):
    """A shard_map body running `iters` chained collectives.

    The scan carries a data dependency through every iteration so XLA
    cannot elide or overlap the timed region away.
    """
    def body(x):
        def one(carry, _):
            if collective == "psum":
                out = lax.psum(carry, axis)
                # renormalize so values stay finite across iterations
                out = out / axis_size(axis)
            elif collective == "all_gather":
                gathered = lax.all_gather(carry, axis)
                out = gathered.mean(axis=0) + carry * 0.0
            elif collective == "reduce_scatter":
                out = lax.psum_scatter(
                    jnp.tile(carry, (axis_size(axis), 1)),
                    axis, scatter_dimension=0, tiled=True,
                ) / axis_size(axis)
            elif collective == "ppermute":
                n = axis_size(axis)
                perm = [(i, (i + 1) % n) for i in range(n)]
                out = lax.ppermute(carry, axis, perm)
            else:
                raise ValueError(collective)
            return out, None

        out, _ = lax.scan(one, x, None, length=iters)
        return out

    return body


def collective_bandwidth(
    mesh: Mesh,
    axis: str = "ici",
    payload_mb: float = 32.0,
    iters: int = 20,
    dtype=jnp.bfloat16,
) -> Dict[str, float]:
    """GB/s/chip for each collective over ``axis`` of ``mesh``.

    Payload is the per-chip shard size.  Returns
    {collective: algorithmic GB/s/chip} plus bookkeeping keys.
    """
    from dcos_commons_tpu.parallel.compat import shard_map

    n = mesh.shape[axis]
    bytes_per_elem = jnp.dtype(dtype).itemsize
    elems = int(payload_mb * 1e6 / bytes_per_elem)
    # 2D [rows, 128]: lane-friendly layout on TPU
    rows = max(elems // 128, 8)
    shard = jnp.ones((rows, 128), dtype)
    payload_bytes = shard.size * bytes_per_elem

    results: Dict[str, float] = {
        "axis_size": float(n),
        "payload_mb_per_chip": round(payload_bytes / 1e6, 2),
        "iters": float(iters),
    }
    if n < 2:
        return results
    # [n*rows, 128] sharded on rows: each chip's local block is `shard`
    replicated = jnp.tile(shard, (n, 1))
    overhead = _dispatch_overhead_s()

    for name, factor in _ALGO_FACTOR.items():
        fn = jax.jit(
            shard_map(
                _bench_fn(name, axis, iters),
                mesh=mesh,
                in_specs=P(axis),
                out_specs=P(axis),
                check_vma=False,
            )
        )
        out = fn(replicated)  # compile
        _sync(out)
        t0 = time.monotonic()
        out = fn(replicated)
        _sync(out)
        dt = max(time.monotonic() - t0 - overhead, 1e-6)
        moved = factor(n) * payload_bytes * iters
        results[name + "_gbps_per_chip"] = round(moved / dt / 1e9, 3)
    return results


def _dispatch_overhead_s() -> float:
    """Per-call dispatch + readback latency, measured with a trivial
    program — dominant on relayed/tunneled devices, subtracted from
    every roofline timing below."""
    tiny = jnp.ones((8, 128), jnp.float32)
    noop = jax.jit(lambda x: x + 1.0)
    _sync(noop(tiny))
    t0 = time.monotonic()
    _sync(noop(tiny))
    return time.monotonic() - t0


def single_chip_rooflines(
    payload_mb: float = 256.0,
    iters: int = 20,
    chain_floor: int = 2000,
    matmul_dim: int = 4096,
) -> Dict[str, float]:
    """HBM copy GB/s and bf16 matmul TFLOPs on the default device —
    the ceilings any collective/compute number sits under.

    ``iters`` is a floor; chains are lengthened (chain_floor) so
    on-device work DWARFS the ~200ms relay dispatch latency —
    with short chains the overhead subtraction's own noise can
    push results past physical peak — and the measured per-call
    overhead is subtracted from each timing.
    """
    out: Dict[str, float] = {}
    overhead = _dispatch_overhead_s()
    out["dispatch_overhead_ms"] = round(overhead * 1e3, 1)

    # HBM bandwidth: chained whole-array copies (read + write per iter)
    copy_iters = max(iters, chain_floor)
    elems = int(payload_mb * 1e6 / 2)
    rows = max(elems // 128, 8)
    x = jnp.ones((rows, 128), jnp.bfloat16)
    nbytes = x.size * 2

    @jax.jit
    def copy_chain(x):
        def one(carry, _):
            return carry + 1.0, None
        y, _ = lax.scan(one, x, None, length=copy_iters)
        return y

    y = copy_chain(x)
    _sync(y)
    t0 = time.monotonic()
    y = copy_chain(x)
    _sync(y)
    dt = max(time.monotonic() - t0 - overhead, 1e-6)
    out["hbm_copy_gbps"] = round(2 * nbytes * copy_iters / dt / 1e9, 3)

    # MXU roofline: chained bf16 matmuls (4k x 4k fills the MXU)
    mm_iters = max(iters, chain_floor)
    m = matmul_dim
    a = jnp.ones((m, m), jnp.bfloat16)

    @jax.jit
    def matmul_chain(a):
        def one(carry, _):
            prod = jnp.dot(carry, carry, preferred_element_type=jnp.bfloat16)
            return prod / jnp.float32(m).astype(jnp.bfloat16), None
        y, _ = lax.scan(one, a, None, length=mm_iters)
        return y

    y = matmul_chain(a)
    _sync(y)
    t0 = time.monotonic()
    y = matmul_chain(a)
    _sync(y)
    dt = max(time.monotonic() - t0 - overhead, 1e-6)
    out["matmul_bf16_tflops"] = round(2 * m ** 3 * mm_iters / dt / 1e12, 3)
    return out


def main(argv: Optional[list] = None) -> int:
    """CLI for the sidecar bench task (frameworks/jax collective plan).

    Multi-process mode rendezvous through jax.distributed using the
    gang env the evaluator injects (COORDINATOR_ADDRESS et al.); single
    chip falls back to rooflines.
    """
    import argparse
    import json
    import os

    parser = argparse.ArgumentParser(prog="collective-bench")
    parser.add_argument("--payload-mb", type=float, default=32.0)
    parser.add_argument("--iters", type=int, default=20)
    args = parser.parse_args(argv)

    if os.environ.get("COORDINATOR_ADDRESS"):
        from dcos_commons_tpu.parallel.distributed import initialize_from_env

        initialize_from_env()
    devices = jax.devices()
    report: Dict[str, object] = {
        "devices": len(devices),
        "platform": devices[0].platform,
    }
    if len(devices) >= 2:
        mesh = Mesh(devices, ("ici",))
        report.update(
            collective_bandwidth(
                mesh, "ici", payload_mb=args.payload_mb, iters=args.iters
            )
        )
    report.update(single_chip_rooflines(iters=args.iters))
    print(json.dumps(report, sort_keys=True))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
