"""Metrics registry: exposition typing and StatsD push.

Reference: metrics/Metrics.java — counters AND timers push to StatsD
when STATSD_UDP_HOST/PORT are set (Metrics.java:74-79), and the
Prometheus exposition types monotonic counters as ``counter`` so
downstream ``rate()`` works.
"""

import socket

from dcos_commons_tpu.metrics.registry import Metrics


def test_timer_samples_window_survives_ring_trim():
    """Phase-window callers (bench_fleet_scale) read timer_count()
    before a phase and timer_samples(since_count=...) after; the
    window must stay correct even when the 256-sample ring trims."""
    m = Metrics()
    for _ in range(10):
        with m.time("t"):
            pass
    n0 = m.timer_count("t")
    assert n0 == 10
    assert m.timer_samples("t", since_count=n0) == []
    for _ in range(5):
        with m.time("t"):
            pass
    assert len(m.timer_samples("t", since_count=n0)) == 5
    assert len(m.timer_samples("t")) == 15
    # trim past the boundary: only the retained newest samples return
    for _ in range(300):
        with m.time("t"):
            pass
    windowed = m.timer_samples("t", since_count=n0)
    assert len(windowed) == 256  # ring cap, not 305
    assert m.timer_count("t") == 315


def test_prometheus_types_counters_as_counter():
    m = Metrics()
    m.incr("operations.launch", 3)
    m.incr("task_status.TASK_RUNNING")
    m.gauge("offers.snapshot_cache.hit", lambda: 5.0)
    with m.time("cycle.process"):
        pass
    text = m.prometheus()
    lines = text.splitlines()

    # monotonic incr() entries expose as counter
    assert "# TYPE operations_launch counter" in lines
    assert "operations_launch 3.0" in lines
    assert "# TYPE task_status_task_running counter" in lines
    # registered gauges stay gauges
    assert "# TYPE offers_snapshot_cache_hit gauge" in lines
    # every timer aggregate (count/min/mean/max/p95) is a gauge: the
    # window re-aggregates, so none of them is monotonic
    timer_types = [
        line for line in lines
        if line.startswith("# TYPE cycle_process")
    ]
    assert timer_types and all(t.endswith("gauge") for t in timer_types)
    # exposition shape: every TYPE line is followed by its sample
    for i, line in enumerate(lines):
        if line.startswith("# TYPE "):
            metric = line.split()[2]
            assert lines[i + 1].startswith(metric + " ")


def test_statsd_receives_counter_and_timing_datagrams(monkeypatch):
    sink = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    sink.bind(("127.0.0.1", 0))
    sink.settimeout(5)
    port = sink.getsockname()[1]
    monkeypatch.setenv("STATSD_UDP_HOST", "127.0.0.1")
    monkeypatch.setenv("STATSD_UDP_PORT", str(port))
    try:
        m = Metrics()
        m.incr("offers.evaluated")
        datagram = sink.recv(1024).decode()
        assert datagram == "offers.evaluated:1.0|c"

        # timers push |ms datagrams too (the satellite fix: time()
        # used to record locally and never push)
        with m.time("cycle.evaluate"):
            pass
        datagram = sink.recv(1024).decode()
        name, _, payload = datagram.partition(":")
        assert name == "cycle.evaluate"
        value, _, kind = payload.partition("|")
        assert kind == "ms"
        assert float(value) >= 0.0
    finally:
        sink.close()


def test_no_statsd_configured_is_silent(monkeypatch):
    monkeypatch.delenv("STATSD_UDP_HOST", raising=False)
    monkeypatch.delenv("STATSD_UDP_PORT", raising=False)
    m = Metrics()
    m.incr("x")
    with m.time("y"):
        pass
    assert m.snapshot()["x"] == 1.0
