"""Live KV page migration (ISSUE 16): the fenced cutover protocol,
chaos kills at every boundary, and the three consumers built on it.

Coverage layers, all against a deterministic fake model (the
``test_paged_kv`` chain: every token a pure function of its
predecessor and position, so "zero token loss, none doubled" is a
list equality, not a statistic):

* PROTOCOL: a session moved mid-generation produces the exact oracle
  continuation on the destination; chaos kills at every stage
  boundary (mid-snapshot, mid-stream, mid-splice, pre-cutover,
  post-cutover-pre-ack) leave exactly one serving copy, no leaked or
  double-freed pages on either pod (``PageAllocator.check_invariants``
  on both), and the post-cutover failure is retryable-release, never
  a resumed source.

* SPLICE TRANSACTIONALITY: a hypothesis sweep splices fabricated
  sessions (random geometry, random arena pressure) into a pod and
  aborts them — admission is the same transactional rule a fresh
  request faces, so invariants hold after every op and a failed or
  aborted splice restores the arena byte-for-byte.

* CONSUMERS: drain-with-migration moves every live session and its
  report re-points router prefix claims; the router follows a
  migrated session with a collect and routes long prompts to
  prefill-role capacity; prefill pods hand finished pages to decode
  pools and degrade to local decode when no pool answers; role-aware
  health judges a prefill pod on prefill backlog, never on decode
  occupancy (the QuietPodWatcher flap this would otherwise cause is
  the ISSUE's satellite).
"""

import threading
import time

import numpy as np
import pytest

from dcos_commons_tpu.health.detectors import (
    QuietPodWatcher,
    ServingSloWatcher,
)
from dcos_commons_tpu.router.core import RequestRouter
from dcos_commons_tpu.serve.engine import PagedEngine
from dcos_commons_tpu.serve.migration import (
    STAGES,
    InProcessTransport,
    MigrationError,
    PrefillHandoff,
    ReleasePendingError,
    SessionMigratedError,
    SessionSnapshot,
    drain_sessions,
    migrate_session,
)

_V = 97
P = 4  # page tokens


@pytest.fixture(scope="module", autouse=True)
def _racecheck_probes():
    """Dynamic race probes (SDKLINT_RACECHECK=1): migration splices KV
    state into a live decode loop from a foreign thread — watch the
    engine classes' shared-write set so any unordered splice/tick pair
    fails the run (the PR 16 bug class).  No-op in the fast tier."""
    from dcos_commons_tpu.serve.engine import SlotEngine
    from dcos_commons_tpu.utils.microbatch import MicroBatcher

    from conftest import racecheck_watch_guard

    yield from racecheck_watch_guard(PagedEngine, SlotEngine, MicroBatcher)


def _chain_first(prompt):
    return (sum(prompt) * 31 + len(prompt)) % _V


def _chain_next(tok, pos):
    return (tok * 7 + pos * 3 + 1) % _V


def _chain_oracle(prompt, n, eos=None):
    out = [_chain_first(prompt)]
    pos = len(prompt)
    while len(out) < n and (eos is None or out[-1] != eos):
        out.append(_chain_next(out[-1], pos))
        pos += 1
    return out


class ChainArena:
    """The fake device half: a dict-of-dicts KV arena whose cell
    contents are the tokens themselves, so a migrated page's payload
    is CONTENT-CHECKABLE — a destination decoding from wrong bytes
    would still produce the right chain (decode is a function of
    token and position), but prefill resume reads the cells, and the
    page-level export/import contract is exercised for real."""

    def __init__(self, step_s=0.004):
        self.cells = {}
        self.lock = threading.Lock()
        self.step_s = step_s

    def prefill_chunk(self, padded, slot, table, start, true_len,
                      temp, seed):
        with self.lock:
            buf = [
                self.cells[int(table[pos // P])][pos % P]
                for pos in range(start)
            ]
            for i in range(true_len):
                pos = start + i
                page = int(table[pos // P])
                tok = int(padded[0, i])
                self.cells.setdefault(page, {})[pos % P] = tok
                buf.append(tok)
        return _chain_first(buf)

    def decode(self, tok, pos, temps, seeds, tables, n_active):
        time.sleep(self.step_s)
        with self.lock:
            for s in range(len(tok)):
                if int(pos[s]) > 0:
                    page = int(tables[s][int(pos[s]) // P])
                    if page != 0:
                        self.cells.setdefault(page, {})[
                            int(pos[s]) % P
                        ] = int(tok[s])
        return np.asarray(
            [_chain_next(int(t), int(q)) for t, q in zip(tok, pos)],
            np.int32,
        )

    def read_page(self, page):
        with self.lock:
            return dict(self.cells.get(page, {}))

    def write_page(self, page, payload):
        with self.lock:
            self.cells[page] = dict(payload)


def _make_pod(role="unified", handoff=None, pages=40, slots=3,
              step_s=0.004):
    arena = ChainArena(step_s=step_s)
    eng = PagedEngine(
        arena.prefill_chunk, arena.decode, slots, 64, 48,
        page_tokens=P, pages=pages, chunk_tokens=8, prefix_cache=True,
        role=role, read_page=arena.read_page,
        write_page=arena.write_page, handoff=handoff,
        queue_timeout_s=30,
    )
    return arena, eng


def _submit_async(eng, prompt, n, result, key="r"):
    def client():
        try:
            result[key] = eng.submit([prompt], n)
        except BaseException as e:  # noqa: BLE001 — the assertion target
            result[key] = e

    t = threading.Thread(target=client, daemon=True)
    t.start()
    return t


def _wait_mid_decode(eng, min_out=4, timeout=10.0):
    """Block until the single live session is decoding with at least
    ``min_out`` tokens out; returns its rid."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        sess = eng.sessions()
        if sess and sess[0]["state"] == "decode" \
                and eng.stats()["tokens_out"] >= min_out:
            return sess[0]["rid"]
        time.sleep(0.005)
    raise AssertionError("session never reached mid-decode")


# -- the wire format ---------------------------------------------------


def test_snapshot_wire_roundtrip_is_json_safe():
    import json

    snap = SessionSnapshot(
        rid=7, tokens=[1, 2, 3], max_new=9, temperature=0.5, eos=42,
        seed=123, out=[11, 12], fill_pos=3, kv_end=4, page_tokens=P,
        pages=[
            (0, np.arange(8, dtype=np.float32).reshape(2, 4)),
            (1, {0: 5, 3: 9}),               # fake-arena cell dict
            (2, {"k": np.zeros(2, np.int8)}),
        ],
        source="pod-0",
    )
    wire = json.loads(json.dumps(snap.to_wire()))  # must survive JSON
    back = SessionSnapshot.from_wire(wire)
    assert back.tokens == snap.tokens and back.out == snap.out
    assert back.eos == 42 and back.kv_end == 4
    assert np.array_equal(back.pages[0][1], snap.pages[0][1])
    assert back.pages[0][1].dtype == np.float32
    assert back.pages[1][1] == {0: 5, 3: 9}  # int keys survive
    assert np.array_equal(back.pages[2][1]["k"], np.zeros(2, np.int8))
    assert back.nbytes() == snap.nbytes()


# -- the protocol ------------------------------------------------------


def test_mid_generation_migration_greedy_equal():
    """The tentpole contract: freeze mid-decode, move, and the
    destination finishes the EXACT oracle continuation — zero tokens
    lost, none doubled — while both arenas stay invariant-clean and
    the source's pages all come home."""
    _sa, src = _make_pod()
    _da, dst = _make_pod()
    try:
        free0 = src.stats()["kv_pages_free"]
        prompt = list(range(1, 14))
        n = 30
        result = {}
        t = _submit_async(src, prompt, n, result)
        rid = _wait_mid_decode(src, min_out=5)
        transport = InProcessTransport()
        record = migrate_session(
            src, dst, rid, dest_name="dst", transport=transport
        )
        assert record.ok and record.stage == "release"
        assert record.pages > 0 and record.bytes > 0
        t.join(timeout=15)
        err = result["r"]
        assert isinstance(err, SessionMigratedError), err
        assert err.moved_to == "dst" and err.dest_rid == record.dest_rid
        out = dst.collect(err.dest_rid, timeout=20)
        assert out == _chain_oracle(prompt, n)
        src._allocator.check_invariants()
        dst._allocator.check_invariants()
        assert src.stats()["migrations_out"] == 1
        assert dst.stats()["migrations_in"] == 1
        assert transport.sessions == 1 and transport.bytes_sent > 0
        # every page the moved session held came back: free again, or
        # parked reclaimable in the prefix cache — nothing leaked
        stats = src.stats()
        assert stats["kv_pages_free"] + \
            stats["kv_pages_reclaimable"] == free0
        assert src.sessions() == []
    finally:
        src.stop()
        dst.stop()


@pytest.mark.parametrize("stage", ["snapshot", "stream", "splice",
                                   "cutover"])
def test_chaos_kill_before_cutover_resumes_source(stage):
    """A death at any PRE-cutover boundary aborts cleanly: the
    destination keeps nothing, the source resumes exactly where it
    froze, and the client's reply is the untouched oracle — the
    failed move is invisible except in the record."""
    assert stage in STAGES
    _sa, src = _make_pod()
    _da, dst = _make_pod()
    try:
        dst_free0 = dst.stats()["kv_pages_free"]
        prompt = [3, 1, 4, 1, 5, 9, 2, 6]
        n = 24
        result = {}
        t = _submit_async(src, prompt, n, result)
        rid = _wait_mid_decode(src)

        class ChaosKill(RuntimeError):
            pass

        def chaos(at):
            if at == stage:
                raise ChaosKill(at)

        with pytest.raises(ChaosKill):
            migrate_session(src, dst, rid, dest_name="dst",
                            chaos=chaos)
        # nothing activated: the destination is untouched
        assert dst.sessions() == []
        assert dst.stats()["migrations_in"] == 0
        assert dst.stats()["kv_pages_free"] == dst_free0
        # the source resumed and finishes the generation itself
        t.join(timeout=15)
        assert result["r"] == [_chain_oracle(prompt, n)]
        assert src.stats()["migrations_out"] == 0
        src._allocator.check_invariants()
        dst._allocator.check_invariants()
    finally:
        src.stop()
        dst.stop()


def test_chaos_kill_at_release_is_exactly_once():
    """The worst boundary: cutover landed, release died.  The source
    must NOT resume (that would double-decode); the only legal
    continuation is retrying the release — after which the client is
    redirected and the destination's reply is the one oracle copy."""
    _sa, src = _make_pod()
    _da, dst = _make_pod()
    try:
        prompt = [2, 7, 1, 8, 2, 8]
        n = 26
        result = {}
        t = _submit_async(src, prompt, n, result)
        rid = _wait_mid_decode(src)

        def chaos(at):
            if at == "release":
                raise RuntimeError("killed post-cutover pre-ack")

        with pytest.raises(ReleasePendingError) as exc:
            migrate_session(src, dst, rid, dest_name="dst",
                            chaos=chaos)
        pending = exc.value
        assert pending.rid == rid and pending.moved_to == "dst"
        # the destination OWNS the session: cutover is final, so a
        # late abort must refuse (no-op) rather than kill the row
        dst.abort_splice(pending.dest_rid)
        assert dst.stats()["migrations_in"] == 1
        # the source row is still frozen — not serving, not released:
        # sessions() lists only unfenced rows
        assert src.sessions() == []
        assert not t.join(timeout=0.2) and t.is_alive()
        # retried release (idempotent per rid) completes the protocol
        src.release_migrated(rid, moved_to="dst",
                             dest_rid=pending.dest_rid)
        t.join(timeout=15)
        err = result["r"]
        assert isinstance(err, SessionMigratedError)
        out = dst.collect(pending.dest_rid, timeout=20)
        assert out == _chain_oracle(prompt, n)  # exactly once
        assert src.stats()["migrations_out"] == 1
        src._allocator.check_invariants()
        dst._allocator.check_invariants()
    finally:
        src.stop()
        dst.stop()


# -- splice transactionality (hypothesis) ------------------------------


def _fabricated_snapshot(tokens, out, max_new, fill):
    """A wire-faithful snapshot for splice-admission properties: the
    page payloads carry the chain cells a real export would."""
    from dcos_commons_tpu.serve.paging import pages_for

    plen = len(tokens)
    fill_pos = min(fill, plen)
    kv_end = plen + len(out) - 1 if fill_pos >= plen and out \
        else fill_pos
    seq = list(tokens) + list(out)
    pages = []
    for v in range(pages_for(kv_end, P) if kv_end > 0 else 0):
        cells = {
            pos - v * P: seq[pos]
            for pos in range(v * P, min((v + 1) * P, kv_end))
        }
        pages.append((v, cells))
    return SessionSnapshot(
        rid=0, tokens=list(tokens), max_new=max_new, temperature=0.0,
        eos=None, seed=1, out=list(out), fill_pos=fill_pos,
        kv_end=kv_end, page_tokens=P, pages=pages,
    )


def _engine_private_pages(eng):
    """Every page privately owned by a live engine row (slotted,
    prefilling, or parked by splice) — the ``private_pages`` argument
    the allocator's conservation check expects."""
    with eng._cv:
        rows = {r for r in eng._rows if r is not None}
        rows |= set(eng._prefilling)
        rows |= set(eng._spliced.values())
        rows |= set(eng._migrated.values())
        return [p for r in rows for p in r.private_pages]


def test_splice_preserves_allocator_invariants():
    """Property: any sequence of splice/abort against a pod under
    arbitrary fabricated-session geometry preserves the allocator
    invariants at EVERY step, and a full abort pass restores the free
    count exactly — splice admission is transactional (a denied
    admission or missing page leaves no residue)."""
    hyp = pytest.importorskip("hypothesis")
    st = pytest.importorskip("hypothesis.strategies")

    session = st.tuples(
        st.lists(st.integers(0, _V - 1), min_size=1, max_size=24),
        st.lists(st.integers(0, _V - 1), min_size=1, max_size=12),
        st.integers(1, 20),
        st.integers(0, 40),
    )

    @hyp.settings(max_examples=20, deadline=None)
    @hyp.given(st.lists(session, min_size=1, max_size=5),
               st.integers(6, 30))
    def run(sessions, arena_pages):
        _a, pod = _make_pod(pages=arena_pages)
        try:
            free0 = pod.stats()["kv_pages_free"]
            spliced = []
            for tokens, out, extra, fill in sessions:
                snap = _fabricated_snapshot(
                    tokens, out, max_new=len(out) + extra, fill=fill
                )
                if len(tokens) + snap.max_new > 64:
                    continue
                try:
                    spliced.append(pod.splice(snap))
                except MigrationError:
                    pass  # denied admission must leave no residue
                pod._allocator.check_invariants(
                    _engine_private_pages(pod))
            for rid in spliced:
                pod.abort_splice(rid)
                pod._allocator.check_invariants(
                    _engine_private_pages(pod))
            assert pod.stats()["kv_pages_free"] == free0
        finally:
            pod.stop()

    run()


def test_splice_abort_sweep_restores_arena():
    """Deterministic complement to the hypothesis property (runs even
    where hypothesis is absent): a seeded sweep of splice/abort under
    varied geometry and arena pressure leaves zero residue."""
    import random

    rng = random.Random(7)
    for arena_pages in (6, 12, 30):
        _a, pod = _make_pod(pages=arena_pages)
        try:
            free0 = pod.stats()["kv_pages_free"]
            spliced = []
            for _ in range(12):
                plen = rng.randint(1, 24)
                n_out = rng.randint(1, 12)
                snap = _fabricated_snapshot(
                    [rng.randrange(_V) for _ in range(plen)],
                    [rng.randrange(_V) for _ in range(n_out)],
                    max_new=n_out + rng.randint(1, 20),
                    fill=rng.randint(0, plen),
                )
                if plen + snap.max_new > 64:
                    continue
                try:
                    spliced.append(pod.splice(snap))
                except MigrationError:
                    pass
                pod._allocator.check_invariants(
                    _engine_private_pages(pod))
            for rid in spliced:
                pod.abort_splice(rid)
                pod._allocator.check_invariants(
                    _engine_private_pages(pod))
            assert pod.stats()["kv_pages_free"] == free0
        finally:
            pod.stop()


def test_splice_rejects_incompatible_snapshots_cleanly():
    _a, pod = _make_pod(pages=10)
    try:
        free0 = pod.stats()["kv_pages_free"]
        # geometry mismatch
        bad = _fabricated_snapshot([1, 2, 3], [4], max_new=4, fill=3)
        bad.page_tokens = 8
        with pytest.raises(MigrationError, match="geometry"):
            pod.splice(bad)
        # missing page payloads
        holey = _fabricated_snapshot(list(range(9)), [4, 5],
                                     max_new=6, fill=9)
        holey.pages = holey.pages[:1]
        with pytest.raises(MigrationError, match="missing pages"):
            pod.splice(holey)
        # too big for the whole arena
        huge = _fabricated_snapshot(list(range(40)), [1],
                                    max_new=20, fill=40)
        with pytest.raises(MigrationError):
            pod.splice(huge)
        assert pod.stats()["kv_pages_free"] == free0
        pod._allocator.check_invariants()
    finally:
        pod.stop()


# -- drain-with-migration ----------------------------------------------


def test_drain_sessions_moves_every_live_session():
    _sa, src = _make_pod()
    _d1, dst_big = _make_pod(pages=40)
    _d2, dst_small = _make_pod(pages=12)
    try:
        prompts = [[1, 2, 3, 4, 5], [9, 8, 7], [4, 4, 4, 4]]
        n = 28
        results = [{} for _ in prompts]
        threads = [
            _submit_async(src, p, n, r)
            for p, r in zip(prompts, results)
        ]
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and (
            len(src.sessions()) < len(prompts)
            or any(s["state"] != "decode" for s in src.sessions())
        ):
            time.sleep(0.005)
        report = drain_sessions(
            src, {"big": dst_big, "small": dst_small},
        )
        assert len(report) == len(prompts)
        assert all(row["ok"] for row in report), report
        # the report carries the prompt for claim re-pointing
        assert sorted(tuple(r["tokens"]) for r in report) == \
            sorted(tuple(p) for p in prompts)
        for t in threads:
            t.join(timeout=15)
        by_prompt = {
            tuple(r["tokens"]): r for r in report
        }
        dests = {"big": dst_big, "small": dst_small}
        for prompt, result in zip(prompts, results):
            err = result["r"]
            assert isinstance(err, SessionMigratedError), err
            out = dests[err.moved_to].collect(err.dest_rid, timeout=20)
            assert out == _chain_oracle(prompt, n)
            assert by_prompt[tuple(prompt)]["dest"] == err.moved_to
        assert src.sessions() == []
        assert src.stats()["migrations_out"] == len(prompts)
        for pod in (src, dst_big, dst_small):
            pod._allocator.check_invariants()
    finally:
        src.stop()
        dst_big.stop()
        dst_small.stop()


def test_drain_with_no_viable_destination_resumes_sessions():
    """A drain that cannot place a session reports ok=False and the
    legacy wait-out covers it — migration never strands a client."""
    _sa, src = _make_pod()
    _da, tiny = _make_pod(pages=3)  # cannot admit anything real
    try:
        prompt = list(range(12))
        n = 20
        result = {}
        t = _submit_async(src, prompt, n, result)
        _wait_mid_decode(src)
        report = drain_sessions(src, {"tiny": tiny})
        assert len(report) == 1 and not report[0]["ok"]
        t.join(timeout=15)
        assert result["r"] == [_chain_oracle(prompt, n)]
        src._allocator.check_invariants()
        tiny._allocator.check_invariants()
    finally:
        src.stop()
        tiny.stop()


# -- the router consumers ----------------------------------------------


def _router(send, **kw):
    r = RequestRouter(send, page_tokens=P, **kw)
    return r


def test_router_drain_with_migration_repoints_claims():
    r = _router(lambda n, a, req: [[1]])
    r.update_pods({"pod-0": {"address": "h0:1"},
                   "pod-1": {"address": "h1:1"}})
    prompt = list(range(16))
    # park claims on pod-0 through the public request path
    for p in ("pod-0", "pod-1"):
        r.observe_stats(p, {"queue_depth": 0, "stats_age_s": 0.0,
                            "t": time.time()})
    while r.route(prompt) != "pod-0":
        r.update_pods({"pod-0": {"address": "h0:1"},
                       "pod-1": {"address": "h1:1"}},
                      generation=None)
        break
    r.submit(prompt, 4)
    owner = r._affinity.claims_by_pod()
    (claimed_pod,) = owner
    other = "pod-1" if claimed_pod == "pod-0" else "pod-0"
    claims = owner[claimed_pod]
    # drain WITH migration: claims re-point to the destination
    assert r.drain(claimed_pod, migrated_to=other)
    assert r._affinity.claims_by_pod() == {other: claims}
    assert r.stats()["router_chain_repoints"] == claims
    # the drained pod no longer takes traffic
    assert r.route(prompt) == other
    # legacy drain (no destination): the other pod's claims die
    assert r.drain(other)
    assert r._affinity.claims_by_pod() == {}


def test_router_repoint_prompt_moves_one_chain():
    r = _router(lambda n, a, req: [[1]])
    r.update_pods({"pod-0": {"address": "h0:1"},
                   "pod-1": {"address": "h1:1"}})
    prompt = list(range(12))
    r.submit(prompt, 4)
    moved = r.repoint_prompt(prompt, "pod-1")
    assert moved > 0
    assert r._affinity.claims_by_pod() == {"pod-1": moved}


def test_router_follows_migrated_session():
    """A pod answering 409-migrated mid-request: the router collects
    from the destination and the client sees one seamless reply."""
    calls = []

    def send(name, address, request):
        calls.append((name, dict(request)))
        if "collect" in request:
            assert name == "pod-1"
            assert request["collect"] == 55
            return [[7, 8, 9]]
        raise SessionMigratedError(3, "pod-1", 55)

    r = _router(send)
    r.update_pods({"pod-0": {"address": "h0:1"},
                   "pod-1": {"address": "h1:1"}})
    # make pod-0 the routed target (fresh, lower load)
    r.observe_stats("pod-0", {"queue_depth": 0, "stats_age_s": 0.0,
                              "t": time.time()})
    out = r.submit([1, 2, 3], 8)
    assert out == [7, 8, 9]
    assert r.stats()["router_migration_follows"] == 1
    assert calls[-1][1] == {"collect": 55}


def test_router_routes_long_prompts_to_prefill_pods():
    sent = []
    r = _router(lambda n, a, req: sent.append(n) or [[1]])
    r.update_pods({
        "prefill-0": {"address": "p0:1", "role": "prefill"},
        "decode-0": {"address": "d0:1", "role": "decode"},
        "decode-1": {"address": "d1:1", "role": "decode"},
    })
    long_prompt = list(range(4 * P))   # the auto threshold
    short_prompt = [1, 2, 3]
    assert r.route(long_prompt) == "prefill-0"
    assert r.route(short_prompt).startswith("decode-")
    assert r.stats()["router_prefill_pods"] == 1
    # inert without prefill capacity: roles all-unified change nothing
    r2 = _router(lambda n, a, req: [[1]])
    r2.update_pods({"pod-0": {"address": "h0:1"},
                    "pod-1": {"address": "h1:1"}})
    assert r2.route(long_prompt) in ("pod-0", "pod-1")


def test_router_role_follows_pod_stats():
    """A pod's own serving_role gauge refines the discovery role —
    the pod is authoritative about its posture."""
    r = _router(lambda n, a, req: [[1]])
    r.update_pods({"pod-0": {"address": "h0:1"},
                   "pod-1": {"address": "h1:1"}})
    r.observe_stats("pod-0", {"serving_role": "prefill",
                              "stats_age_s": 0.0, "t": time.time()})
    assert r.describe()["pods"]["pod-0"]["role"] == "prefill"
    assert r.route(list(range(4 * P))) == "pod-0"


def test_rebalance_suggestion_flags_prefix_hotspot():
    r = _router(lambda n, a, req: [[1]])
    r.update_pods({"hot": {"address": "h0:1"},
                   "cold": {"address": "h1:1"}})
    now = time.time()
    r.observe_stats("hot", {"queue_depth": 9, "stats_age_s": 0.0,
                            "t": now})
    r.observe_stats("cold", {"queue_depth": 0, "stats_age_s": 0.0,
                             "t": now})
    # weld claims onto the hot pod
    for i in range(10):
        r._affinity.record([i + 1], "hot")
    suggestion = r.rebalance_suggestion(min_claims=8, min_skew=2.0)
    assert suggestion is not None
    assert suggestion["from"] == "hot" and suggestion["to"] == "cold"
    assert suggestion["claims"] >= 8 and suggestion["load_gap"] > 0
    # balanced fleet: no suggestion
    for i in range(10):
        r._affinity.record([100 + i], "cold")
    r.observe_stats("cold", {"queue_depth": 9, "stats_age_s": 0.0,
                             "t": time.time()})
    assert r.rebalance_suggestion(min_claims=8, min_skew=2.0) is None


# -- role-aware health -------------------------------------------------


def test_prefill_pod_judged_on_backlog_not_occupancy():
    slo = ServingSloWatcher(kv_occupancy_slo=0.9,
                            kv_pages_free_slo=8,
                            prefill_backlog_slo=64,
                            stale_stats_s=0.0)
    # a prefill pod transiently pinning pages between handoffs: its
    # decode-occupancy gauges are meaningless and must not breach
    events = slo.observe({"serve-0-node": {
        "serving_role": "prefill", "kv_occupancy": 0.99,
        "kv_pages_free": 1, "prefill_chunk_backlog": 500,
    }})
    signals = {e["signal"] for e in events}
    assert signals == {"prefill_chunk_backlog"}, events
    # the same gauges on a unified pod breach both kv signals
    slo2 = ServingSloWatcher(kv_occupancy_slo=0.9,
                             kv_pages_free_slo=8,
                             prefill_backlog_slo=64,
                             stale_stats_s=0.0)
    events = slo2.observe({"serve-0-node": {
        "serving_role": "unified", "kv_occupancy": 0.99,
        "kv_pages_free": 1, "prefill_chunk_backlog": 500,
    }})
    assert {e["signal"] for e in events} == {
        "kv_occupancy", "kv_pages_free", "prefill_chunk_backlog"
    }


def test_quiet_watcher_ignores_prefill_idle_decode_gauges():
    """The flap fix: a prefill pod saturated with prompt work is NOT
    quiet (its backlog says so), even though its decode gauges sit at
    idle values by design; a genuinely idle prefill pod IS quiet."""
    slo = ServingSloWatcher(kv_occupancy_slo=0.9,
                            prefill_backlog_slo=64,
                            stale_stats_s=0.0)
    quiet = QuietPodWatcher(slo, quiet_factor=0.25)
    busy = {"serving_role": "prefill", "kv_occupancy": 0.0,
            "prefill_chunk_backlog": 500}
    assert quiet._is_quiet(busy, {}) is False
    idle = {"serving_role": "prefill", "kv_occupancy": 0.0,
            "prefill_chunk_backlog": 0}
    assert quiet._is_quiet(idle, {}) is True
    # a unified pod's occupancy still attests load the usual way
    loaded = {"serving_role": "unified", "kv_occupancy": 0.8,
              "prefill_chunk_backlog": 0}
    assert quiet._is_quiet(loaded, {}) is False


# -- prefill/decode disaggregation -------------------------------------


def test_prefill_handoff_streams_finished_pages_to_decode_pool():
    pods = {}
    handoff = PrefillHandoff(lambda: pods)
    _pa, prefill = _make_pod(role="prefill", handoff=handoff)
    _d1, decode_a = _make_pod(role="decode", pages=40)
    _d2, decode_b = _make_pod(role="decode", pages=12)
    pods["decode-a"] = decode_a
    pods["decode-b"] = decode_b
    try:
        prompt = list(range(1, 14))
        n = 30
        with pytest.raises(SessionMigratedError) as exc:
            prefill.submit([prompt], n)
        err = exc.value
        # ranked by free pages: the big pool wins
        assert err.moved_to == "decode-a"
        out = pods[err.moved_to].collect(err.dest_rid, timeout=20)
        assert out == _chain_oracle(prompt, n)
        assert handoff.handoffs == 1 and handoff.fallbacks == 0
        assert prefill.stats()["serving_role"] == "prefill"
        assert prefill.sessions() == []
        for pod in (prefill, decode_a, decode_b):
            pod._allocator.check_invariants()
    finally:
        prefill.stop()
        decode_a.stop()
        decode_b.stop()


def test_prefill_pod_degrades_to_local_decode_without_pool():
    """No decode pod answers: the handoff falls back and the prefill
    pod decodes locally — disaggregation degrades to unified, never
    to a failed request."""
    handoff = PrefillHandoff(lambda: {})
    _pa, prefill = _make_pod(role="prefill", handoff=handoff)
    try:
        prompt = [5, 4, 3, 2, 1]
        n = 16
        out = prefill.submit([prompt], n)
        assert out == [_chain_oracle(prompt, n)]
        assert handoff.fallbacks == 1 and handoff.handoffs == 0
        prefill._allocator.check_invariants()
    finally:
        prefill.stop()
