"""Spec layer tests (mirrors reference YAMLToInternalMappersTest + validate/ tests)."""

import dataclasses

import pytest

from dcos_commons_tpu.specification import (
    ConfigValidationError,
    GoalState,
    ServiceSpec,
    SpecError,
    TpuSpec,
    from_yaml,
    render_template,
    validate_spec_change,
)
from dcos_commons_tpu.specification.specs import (
    ResourceSpec,
    VolumeSpec,
    pod_instance_name,
    task_full_name,
)

HELLO_YAML = """
name: {{FRAMEWORK_NAME}}
user: nobody
pods:
  hello:
    count: {{HELLO_COUNT:-2}}
    placement: 'max-per-host:1'
    tasks:
      server:
        goal: RUNNING
        cmd: "echo hello >> hello-container-path/output && sleep 1000"
        cpus: {{HELLO_CPUS:-0.5}}
        memory: 256
        volume:
          path: hello-container-path
          type: ROOT
          size: 64
        env:
          GREETING: hi
        ports:
          http:
            port: 0
            vip: hello:80
        health-check:
          cmd: "stat hello-container-path/output"
          interval: 15
        readiness-check:
          cmd: "test -f ready"
plans:
  deploy:
    strategy: serial
    phases:
      hello-deploy:
        strategy: parallel
        pod: hello
"""

JAX_YAML = """
name: jax-trainer
pods:
  trainer:
    count: 4
    gang: true
    tpu:
      generation: v5e
      chips-per-host: 4
      topology: 4x4
    tasks:
      worker:
        goal: FINISH
        cmd: "python -m train"
        cpus: 4
        memory: 8192
"""


def test_render_template():
    env = {"A": "1"}
    assert render_template("x={{A}} y={{B:-fallback}}", env) == "x=1 y=fallback"
    with pytest.raises(SpecError) as err:
        render_template("{{MISSING_ONE}} {{MISSING_TWO}}", {})
    assert "MISSING_ONE" in str(err.value)
    assert "MISSING_TWO" in str(err.value)


def test_yaml_to_spec():
    spec = from_yaml(HELLO_YAML, {"FRAMEWORK_NAME": "hello-world"})
    assert spec.name == "hello-world"
    assert spec.user == "nobody"
    pod = spec.pod("hello")
    assert pod.count == 2
    assert pod.placement == "max-per-host:1"
    task = pod.task("server")
    assert task.goal == GoalState.RUNNING
    assert task.resources.cpus == 0.5
    assert task.resources.memory_mb == 256
    assert task.resources.ports[0].name == "http"
    assert task.resources.ports[0].vip == "hello:80"
    assert task.volumes[0] == VolumeSpec(
        container_path="hello-container-path", size_mb=64, type="ROOT"
    )
    assert task.env == {"GREETING": "hi"}
    assert task.health_check.interval_s == 15
    assert task.readiness_check.cmd == "test -f ready"
    assert spec.plans["deploy"]["phases"]["hello-deploy"]["pod"] == "hello"


def test_yaml_tpu_pod():
    spec = from_yaml(JAX_YAML)
    pod = spec.pod("trainer")
    assert pod.gang
    assert pod.tpu == TpuSpec(generation="v5e", chips_per_host=4, topology="4x4")
    assert pod.tpu.total_chips == 16
    assert pod.tpu.topology_dims() == (4, 4)
    assert pod.task("worker").goal == GoalState.FINISH


def test_no_gpus_anywhere():
    """North-star requirement (BASELINE.md): no gpus scalar exists."""
    assert not hasattr(ResourceSpec(), "gpus")


def test_spec_roundtrip():
    spec = from_yaml(HELLO_YAML, {"FRAMEWORK_NAME": "rt"})
    restored = ServiceSpec.from_dict(spec.to_dict())
    assert restored == spec
    assert restored.pod("hello").task("server").health_check == \
        spec.pod("hello").task("server").health_check


def test_instance_naming():
    assert pod_instance_name("hello", 0) == "hello-0"
    assert task_full_name("hello", 1, "server") == "hello-1-server"


def test_yaml_errors():
    with pytest.raises(SpecError):
        from_yaml("name: x\npods: {}")
    with pytest.raises(SpecError):
        from_yaml("pods:\n  a:\n    tasks:\n      t:\n        cmd: x")
    with pytest.raises(SpecError):
        from_yaml("name: x\npods:\n  a: {count: 1}")


# -- validators -------------------------------------------------------


def jax_spec(**overrides):
    spec = from_yaml(JAX_YAML)
    if overrides:
        pod = dataclasses.replace(spec.pods[0], **overrides)
        spec = dataclasses.replace(spec, pods=(pod,))
    return spec


def test_validate_initial_deploy_ok():
    validate_spec_change(None, jax_spec())


def test_validate_name_change_rejected():
    old = jax_spec()
    new = dataclasses.replace(old, name="renamed")
    with pytest.raises(ConfigValidationError):
        validate_spec_change(old, new)


def test_validate_user_change_rejected():
    old = dataclasses.replace(jax_spec(), user="alice")
    new = dataclasses.replace(old, user="bob")
    with pytest.raises(ConfigValidationError):
        validate_spec_change(old, new)


def test_validate_shrink_rejected():
    old = from_yaml(HELLO_YAML, {"FRAMEWORK_NAME": "s", "HELLO_COUNT": "3"})
    new = from_yaml(HELLO_YAML, {"FRAMEWORK_NAME": "s", "HELLO_COUNT": "2"})
    with pytest.raises(ConfigValidationError) as err:
        validate_spec_change(old, new)
    assert "shrink" in str(err.value)
    # growth is fine
    bigger = from_yaml(HELLO_YAML, {"FRAMEWORK_NAME": "s", "HELLO_COUNT": "5"})
    validate_spec_change(old, bigger)


def test_validate_volume_change_rejected():
    old = from_yaml(HELLO_YAML, {"FRAMEWORK_NAME": "s"})
    changed = HELLO_YAML.replace("size: 64", "size: 128")
    new = from_yaml(changed, {"FRAMEWORK_NAME": "s"})
    with pytest.raises(ConfigValidationError) as err:
        validate_spec_change(old, new)
    assert "volume" in str(err.value)


def test_validate_topology_change_rejected():
    old = jax_spec()
    new_yaml = JAX_YAML.replace("topology: 4x4", "topology: 2x2").replace(
        "count: 4", "count: 1"
    )
    new = from_yaml(new_yaml)
    with pytest.raises(ConfigValidationError) as err:
        validate_spec_change(old, new)
    assert "topology" in str(err.value).lower()


def test_validate_gang_count_topology_mismatch():
    bad = jax_spec(count=3)  # 4x4 topology at 4 chips/host implies 4 hosts
    with pytest.raises(ConfigValidationError) as err:
        validate_spec_change(None, bad)
    assert "count 3" in str(err.value)


def test_validate_dns_safe_name():
    # reference: ServiceNameCannotBreakDNS — uppercase/underscore/too-
    # long labels are rejected up front, folders component-by-component
    ok = dataclasses.replace(jax_spec(), name="folder/my-svc-2")
    validate_spec_change(None, ok)
    for bad_name in ("Has_Underscore", "UPPER", "-leading", "a" * 64):
        bad = dataclasses.replace(jax_spec(), name=bad_name)
        with pytest.raises(ConfigValidationError) as err:
            validate_spec_change(None, bad)
        assert "DNS" in str(err.value)


def test_validate_zone_change_rejected():
    old = dataclasses.replace(jax_spec(), zone="z1")
    new = dataclasses.replace(old, zone="z2")
    with pytest.raises(ConfigValidationError) as err:
        validate_spec_change(old, new)
    assert "zone" in str(err.value)


def test_validate_zone_placement_regime_change_rejected():
    # reference ZoneValidator: placement may not START or STOP
    # referencing zones after deployment
    old = from_yaml(HELLO_YAML, {"FRAMEWORK_NAME": "s"})
    zonal_yaml = HELLO_YAML.replace(
        "placement: 'max-per-host:1'", "placement: 'max-per-zone:1'"
    )
    new = from_yaml(zonal_yaml, {"FRAMEWORK_NAME": "s"})
    with pytest.raises(ConfigValidationError) as err:
        validate_spec_change(old, new)
    assert "zones" in str(err.value)
    with pytest.raises(ConfigValidationError):
        validate_spec_change(new, old)  # stopping is equally rejected
    validate_spec_change(new, new)  # on -> on is fine
    # the word 'zone' inside a NON-zonal rule value is not a zone
    # reference: moving between two such hostname regexes is fine
    a = from_yaml(HELLO_YAML.replace(
        "placement: 'max-per-host:1'",
        "placement: 'hostname:regex:tpu-zone1-.*'",
    ), {"FRAMEWORK_NAME": "s"})
    b = from_yaml(HELLO_YAML.replace(
        "placement: 'max-per-host:1'",
        "placement: 'hostname:regex:tpu-rack2-.*'",
    ), {"FRAMEWORK_NAME": "s"})
    validate_spec_change(a, b)


def test_validate_network_change_rejected():
    old = from_yaml(HELLO_YAML, {"FRAMEWORK_NAME": "s"})
    pod = dataclasses.replace(old.pods[0], networks=("overlay",))
    new = dataclasses.replace(old, pods=(pod,))
    with pytest.raises(ConfigValidationError) as err:
        validate_spec_change(old, new)
    assert "networks" in str(err.value)


def test_validate_pre_reserved_role_change_rejected():
    old = from_yaml(HELLO_YAML, {"FRAMEWORK_NAME": "s"})
    pod = dataclasses.replace(old.pods[0], pre_reserved_role="slave_public")
    new = dataclasses.replace(old, pods=(pod,))
    with pytest.raises(ConfigValidationError) as err:
        validate_spec_change(old, new)
    assert "pre-reserved-role" in str(err.value)


def test_validate_finished_task_env_change_rejected():
    # reference TaskEnvCannotChange: a FINISH-goal task's env is frozen
    old = jax_spec()
    pod = old.pods[0]
    task = dataclasses.replace(pod.tasks[0], env={"EPOCHS": "9"})
    new = dataclasses.replace(
        old, pods=(dataclasses.replace(pod, tasks=(task,)),)
    )
    with pytest.raises(ConfigValidationError) as err:
        validate_spec_change(old, new)
    assert "env cannot change" in str(err.value)


def test_validate_gang_toggle_rejected():
    old = jax_spec()
    new = jax_spec(gang=False)
    with pytest.raises(ConfigValidationError) as err:
        validate_spec_change(old, new)
    assert "gang" in str(err.value)


def test_validate_unknown_tpu_generation_rejected():
    bad_yaml = JAX_YAML.replace("generation: v5e", "generation: v99x")
    with pytest.raises(ConfigValidationError) as err:
        validate_spec_change(None, from_yaml(bad_yaml))
    assert "generation" in str(err.value)


def test_validate_role_change_gated_on_deployment():
    from dcos_commons_tpu.specification.validation import ValidationContext

    old = dataclasses.replace(jax_spec(), role="old-role")
    new = dataclasses.replace(old, role="new-role")
    # mid-deploy: rejected
    with pytest.raises(ConfigValidationError) as err:
        validate_spec_change(
            old, new, context=ValidationContext(deployment_completed=False)
        )
    assert "role" in str(err.value)
    # after deployment completes, role migration is allowed
    validate_spec_change(
        old, new, context=ValidationContext(deployment_completed=True)
    )
    # without context (pure call) the migration path stays open
    validate_spec_change(old, new)


def test_validate_secrets_need_provider():
    from dcos_commons_tpu.specification.specs import SecretSpec
    from dcos_commons_tpu.specification.validation import ValidationContext

    spec = jax_spec(secrets=(SecretSpec(secret="creds", env_key="TOKEN"),))
    with pytest.raises(ConfigValidationError) as err:
        validate_spec_change(
            None, spec,
            context=ValidationContext(secrets_provider_present=False),
        )
    assert "secrets provider" in str(err.value)
    validate_spec_change(
        None, spec, context=ValidationContext(secrets_provider_present=True)
    )


def test_validate_slices_need_topology_and_gang():
    # slices without a topology: rejected, not silently single-slice
    bad = JAX_YAML.replace(
        "      topology: 4x4\n", ""
    ).replace("generation: v5e", "generation: v5e\n      slices: 2")
    with pytest.raises(ConfigValidationError) as err:
        validate_spec_change(None, from_yaml(bad))
    assert "requires a topology" in str(err.value)
    # slices without gang: equally rejected
    bad2 = JAX_YAML.replace("gang: true", "gang: false").replace(
        "generation: v5e", "generation: v5e\n      slices: 2"
    ).replace("count: 4", "count: 8")
    with pytest.raises(ConfigValidationError) as err:
        validate_spec_change(None, from_yaml(bad2))
    assert "requires gang" in str(err.value)
    # a correct 2-slice spec passes (count = slices x hosts-per-slice)
    ok = JAX_YAML.replace(
        "generation: v5e", "generation: v5e\n      slices: 2"
    ).replace("count: 4", "count: 8")
    validate_spec_change(None, from_yaml(ok))


def test_default_validator_breadth():
    """Reference config/validate/ has 19 validator classes; parity
    demands the default set covers at least 16 distinct checks."""
    from dcos_commons_tpu.specification.validation import default_validators

    assert len(default_validators()) >= 16


# -- TASKCFG env routing (reference: config/TaskEnvRouter.java:17-30) --

TASKCFG_YAML = """
name: cfg-svc
pods:
  index:
    count: 1
    tasks:
      node:
        goal: RUNNING
        cmd: "sleep 1"
        cpus: 0.1
        memory: 32
        env:
          MODE: yaml-default
  data:
    count: 1
    tasks:
      node:
        goal: RUNNING
        cmd: "sleep 1"
        cpus: 0.1
        memory: 32
"""


def test_taskcfg_all_routes_to_every_pod():
    spec = from_yaml(TASKCFG_YAML, env={"TASKCFG_ALL_FOO": "bar"})
    for pod in spec.pods:
        assert pod.tasks[0].env["FOO"] == "bar"


def test_taskcfg_pod_prefix_scopes_and_wins_over_all():
    spec = from_yaml(
        TASKCFG_YAML,
        env={
            "TASKCFG_ALL_FOO": "everywhere",
            "TASKCFG_INDEX_FOO": "index-only",
            "TASKCFG_INDEX_BAR": "baz",
        },
    )
    index = spec.pod("index")
    data = spec.pod("data")
    assert index.tasks[0].env["FOO"] == "index-only"
    assert index.tasks[0].env["BAR"] == "baz"
    assert data.tasks[0].env["FOO"] == "everywhere"
    assert "BAR" not in data.tasks[0].env


def test_taskcfg_overrides_yaml_env():
    # scheduler-env routing wins over the YAML default so end users can
    # retune a packaged service without editing its YAML
    spec = from_yaml(TASKCFG_YAML, env={"TASKCFG_INDEX_MODE": "tuned"})
    assert spec.pod("index").tasks[0].env["MODE"] == "tuned"
    # non-TASKCFG env vars never leak into task envs
    spec2 = from_yaml(TASKCFG_YAML, env={"RANDOM_HOST_VAR": "x"})
    assert "RANDOM_HOST_VAR" not in spec2.pod("index").tasks[0].env


def test_rlimit_spec_validation_and_roundtrip():
    """Reference: specification/RLimitSpec.java — valid names only,
    soft/hard both-or-neither, soft <= hard; -1 = RLIMIT_INFINITY."""
    import pytest as _pytest

    from dcos_commons_tpu.specification.specs import (
        RLimitSpec,
        ServiceSpec,
        SpecError,
    )
    from dcos_commons_tpu.specification.yaml_spec import from_yaml

    # valid forms
    RLimitSpec(name="RLIMIT_NOFILE", soft=64, hard=128)
    RLimitSpec(name="RLIMIT_CORE", soft=0, hard=0)
    RLimitSpec(name="RLIMIT_CPU")  # named, unlimited
    with _pytest.raises(SpecError, match="not a valid rlimit"):
        RLimitSpec(name="RLIMIT_BOGUS", soft=1, hard=1)
    with _pytest.raises(SpecError, match="set together"):
        RLimitSpec(name="RLIMIT_NOFILE", soft=64)
    with _pytest.raises(SpecError, match="exceeds"):
        RLimitSpec(name="RLIMIT_NOFILE", soft=256, hard=128)
    with _pytest.raises(SpecError, match=">= 0"):
        RLimitSpec(name="RLIMIT_NOFILE", soft=-5, hard=-5)
    # YAML dialect (reference svc.yml:9-13) + serde roundtrip through
    # the ConfigStore path
    spec = from_yaml(
        "name: svc\n"
        "pods:\n"
        "  web:\n"
        "    rlimits:\n"
        "      RLIMIT_NOFILE:\n"
        "        soft: 1024\n"
        "        hard: 2048\n"
        "      RLIMIT_CORE: {}\n"
        "    tasks:\n"
        "      server:\n"
        "        goal: RUNNING\n"
        "        cmd: sleep 1\n"
    )
    pod = spec.pod("web")
    assert pod.rlimits == (
        RLimitSpec(name="RLIMIT_NOFILE", soft=1024, hard=2048),
        RLimitSpec(name="RLIMIT_CORE"),
    )
    assert ServiceSpec.from_dict(spec.to_dict()) == spec


def test_rlimit_yaml_malformed_values_raise_spec_error():
    """Malformed rlimit YAML fails as SpecError WITH pod context, like
    every other spec error — not a bare ValueError/AttributeError."""
    import pytest as _pytest

    from dcos_commons_tpu.specification.specs import SpecError
    from dcos_commons_tpu.specification.yaml_spec import from_yaml

    base = (
        "name: svc\n"
        "pods:\n"
        "  web:\n"
        "    rlimits:\n"
        "{rl}"
        "    tasks:\n"
        "      server: {{goal: RUNNING, cmd: sleep 1}}\n"
    )
    for bad_rl, match in (
        ("      RLIMIT_NOFILE: {soft: 1k, hard: 2048}\n", "non-integer"),
        ("      RLIMIT_CORE: 5\n", "mapping"),
    ):
        with _pytest.raises(SpecError, match=match) as err:
            from_yaml(base.format(rl=bad_rl))
        assert "web" in str(err.value)


# -- validation edge cases (validation.py hardening) -------------------


def test_validate_topology_first_deploy_no_previous_spec():
    """TpuTopologyCannotChange compares against the PREVIOUS target;
    on first deploy there is none and every topology is acceptable —
    the validator must not trip over old=None."""
    from dcos_commons_tpu.specification.validation import (
        tpu_topology_cannot_change,
    )

    assert tpu_topology_cannot_change(None, jax_spec()) == []
    # and through the full default-validator run
    validate_spec_change(None, jax_spec())


def test_validate_multi_error_aggregation():
    """One update violating several validators reports EVERY error in
    one ConfigValidationError (reference: the updater collects all 19
    validators' errors before rejecting) — not just the first."""
    old = dataclasses.replace(jax_spec(), user="alice", region="us-east1")
    new = dataclasses.replace(
        jax_spec(), name="renamed", user="bob", region="eu-west4"
    )
    with pytest.raises(ConfigValidationError) as err:
        validate_spec_change(old, new)
    errors = err.value.errors
    assert len(errors) >= 3
    text = "; ".join(errors)
    assert "name cannot change" in text
    assert "user cannot change" in text
    assert "region cannot change" in text
    # str(exc) carries all of them too (the HTTP 400 payload path)
    assert "user cannot change" in str(err.value)


def test_validator_that_raises_vs_returns():
    """A validator returning errors and one RAISING mid-run must both
    surface — a crashing validator rejects the config naming the
    broken check instead of aborting the remaining validators."""

    def returns_errors(old, new):
        return ["returned error"]

    def crashes(old, new):
        raise RuntimeError("boom")

    def raises_validation_error(old, new):
        raise ConfigValidationError(["raised-as-exception error"])

    with pytest.raises(ConfigValidationError) as err:
        validate_spec_change(
            None,
            jax_spec(),
            validators=[returns_errors, crashes, raises_validation_error],
        )
    errors = err.value.errors
    assert "returned error" in errors
    assert "raised-as-exception error" in errors
    assert any("crashes" in e and "boom" in e for e in errors)


def test_validator_crash_alone_still_rejects():
    def crashes(old, new):
        raise ValueError("bad internal state")

    with pytest.raises(ConfigValidationError) as err:
        validate_spec_change(None, jax_spec(), validators=[crashes])
    assert "crashed" in str(err.value)
