"""Flagship decoder-only transformer LM, designed TPU-first.

Design choices (not a port of anything):
- pure-JAX pytree params; layers STACKED and iterated with lax.scan so
  XLA compiles one layer once regardless of depth (compile-time and
  code-size win over unrolled Python loops)
- bf16 activations + params with f32 RMSNorm statistics, f32 logits
  for the loss: the MXU-native mixed precision recipe
- RoPE positions, grouped-query attention, SwiGLU MLP
- attention via ops.flash_attention (pallas) on one device, or
  parallel.ring.ring_attention when the sequence is sharded on "sp"
- sharding rules map every param to a PartitionSpec over
  (dp, fsdp, tp, sp) for pjit; batch shards over (dp, fsdp), heads
  and ffn over tp, params over fsdp
- optional jax.checkpoint (remat) per layer: recompute activations in
  backward to trade FLOPs for HBM
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dcos_commons_tpu.parallel.compat import axis_size

from dcos_commons_tpu.models.quantize import dequantize_weight as dq
from dcos_commons_tpu.ops.attention import flash_attention
from dcos_commons_tpu.ops.rmsnorm import rms_norm
from dcos_commons_tpu.parallel.pipeline import (
    last_stage_value,
    merge_microbatches,
    pipeline_apply,
    split_microbatches,
)


@dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 32768
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: int = 8          # < n_heads => GQA
    d_ff: int = 1408             # SwiGLU hidden
    max_seq: int = 2048
    rope_theta: float = 10000.0
    dtype: Any = jnp.bfloat16
    remat: bool = True
    # remat granularity: "full" recomputes the whole layer in backward;
    # "save-attn" additionally SAVES each layer's attention output
    # (b*s*d bf16 per layer) so the flash kernel never re-runs.
    # Measured on v5e the extra residual traffic made "save-attn"
    # slightly SLOWER (0.486 vs 0.525 MFU), so "full" is the default.
    remat_policy: str = "full"
    # mixed remat: the last k layers store activations instead of
    # recomputing (see _layer_scan) — each costs ~2.2 GB HBM at the
    # flagship shape and buys back 1/n_layers of the recompute pass
    no_remat_layers: int = 0

    def __post_init__(self) -> None:
        if self.remat_policy not in ("full", "save-attn"):
            raise ValueError(
                f"remat_policy {self.remat_policy!r} not in "
                "('full', 'save-attn')"
            )
        if self.use_ring_attention and self.use_ulysses_attention:
            raise ValueError(
                "pick ONE sequence-parallel recipe: ring or ulysses"
            )
    use_ring_attention: bool = False     # sp: K/V rotate (ppermute)
    use_ulysses_attention: bool = False  # sp: all_to_all head regroup
    sp_axis: str = "sp"
    # MoE flagship variant: n_experts > 0 swaps every layer's dense
    # SwiGLU for a mixture of experts (router + per-expert SwiGLU,
    # models/moe.py) with the switch load-balancing aux loss.  Under
    # jit the expert axis shards over the mesh's ep axis (sharding
    # rules below) and GSPMD inserts the dispatch collectives.
    n_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.5
    moe_aux_weight: float = 0.01
    # routing group size for the jit path: tokens route in groups of
    # up to this many, bounding the one-hot dispatch tensors at
    # group * E * C (C scales with the GROUP, not the global batch —
    # an ungrouped b*s routing would be O(tokens^2) memory)
    moe_group_size: int = 1024
    # dispatch implementation: "onehot" (dense [t,E,C] einsums) or
    # "sorted" (argsort + row gather/scatter, no O(t*E*C) tensors —
    # the pick for large groups); see models/moe.py moe_ffn
    moe_impl: str = "onehot"
    # sequence-chunked cross entropy: the [b, s, vocab] f32 logits are
    # never materialized — each chunk's logits are computed, reduced to
    # a scalar, and rematerialized in backward.  0 = unchunked.
    loss_chunk: int = 0
    # flash-attention tile sizes (VMEM-tunable per chip generation)
    attn_block_q: int = 128
    attn_block_k: int = 128

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


Params = Dict[str, Any]


def config_from_env(env: Dict[str, str], **overrides) -> TransformerConfig:
    """The scheduler-env -> TransformerConfig contract, in ONE place.

    Every worker script (frameworks/jax/{train_worker,serve_worker,
    serve_gang_worker}.py) AND the static sharding analyzer
    (analysis/shardcheck.py) build their config through this function:
    if the mapping drifted between a worker and the analyzer, the
    analyzer would vouch for a model the pod never runs.  ``overrides``
    are keyword fields applied on top (dtype, remat, ...).
    """
    fields = dict(
        vocab=int(env.get("VOCAB", "8192")),
        d_model=int(env.get("D_MODEL", "512")),
        n_layers=int(env.get("N_LAYERS", "4")),
        n_heads=int(env.get("N_HEADS", "8")),
        n_kv_heads=int(env.get("N_KV_HEADS", "8")),
        d_ff=int(env.get("D_FF", "1408")),
        max_seq=int(env.get("SEQ_LEN", "1024")),
        # MoE flagship: N_EXPERTS > 0 swaps dense SwiGLU for the
        # ep-sharded mixture (models/moe.py)
        n_experts=int(env.get("N_EXPERTS", "0")),
    )
    fields.update(overrides)
    return TransformerConfig(**fields)


def init_params(config: TransformerConfig, key: jax.Array) -> Params:
    """Stacked-layer param tree: every per-layer array has a leading
    n_layers axis consumed by lax.scan."""
    keys = jax.random.split(key, 8)
    d, h, kv, hd, f = (
        config.d_model,
        config.n_heads,
        config.n_kv_heads,
        config.head_dim,
        config.d_ff,
    )
    n = config.n_layers
    dt = config.dtype

    def normal(key, shape, scale):
        return (jax.random.normal(key, shape, jnp.float32) * scale).astype(dt)

    layers = {
        "attn_norm": jnp.ones((n, d), dt),
        "wq": normal(keys[1], (n, d, h * hd), d ** -0.5),
        "wk": normal(keys[2], (n, d, kv * hd), d ** -0.5),
        "wv": normal(keys[3], (n, d, kv * hd), d ** -0.5),
        "wo": normal(keys[4], (n, h * hd, d), (h * hd) ** -0.5),
        "mlp_norm": jnp.ones((n, d), dt),
    }
    if config.n_experts > 0:
        # one source of truth for the expert init recipe (router-f32
        # policy, scales): moe.init_moe_params, vmapped over layers
        from dcos_commons_tpu.models.moe import MoEConfig, init_moe_params

        moe_config = MoEConfig(
            d_model=d, d_ff=f, n_experts=config.n_experts,
            top_k=config.moe_top_k,
            capacity_factor=config.moe_capacity_factor, dtype=dt,
        )
        layers.update(jax.vmap(
            lambda k: init_moe_params(moe_config, k)
        )(jax.random.split(keys[5], n)))
    else:
        layers.update({
            "w_gate": normal(keys[5], (n, d, f), d ** -0.5),
            "w_up": normal(keys[6], (n, d, f), d ** -0.5),
            "w_down": normal(keys[7], (n, f, d), f ** -0.5),
        })
    return {
        "embed": normal(keys[0], (config.vocab, d), d ** -0.5),
        "layers": layers,
        "final_norm": jnp.ones((d,), dt),
    }


def sharding_rules(config: TransformerConfig) -> Dict[str, P]:
    """Param path -> PartitionSpec (scaling-book layout):
    heads/ffn over tp, the other big axis over fsdp; MoE expert axes
    over ep (GSPMD then inserts the dispatch collectives)."""
    rules = {
        "embed": P("tp", "fsdp"),
        "layers/attn_norm": P(None, None),
        "layers/wq": P(None, "fsdp", "tp"),
        "layers/wk": P(None, "fsdp", "tp"),
        "layers/wv": P(None, "fsdp", "tp"),
        "layers/wo": P(None, "tp", "fsdp"),
        "layers/mlp_norm": P(None, None),
        "final_norm": P(None),
    }
    if config.n_experts > 0:
        # the expert-axis rules live next to the MoE model so the
        # dispatch layout and its sharding can't drift apart
        from dcos_commons_tpu.models.moe import moe_sharding_rules

        rules.update(moe_sharding_rules(prefix="layers/", stacked=True))
    else:
        rules.update({
            "layers/w_gate": P(None, "fsdp", "tp"),
            "layers/w_up": P(None, "fsdp", "tp"),
            "layers/w_down": P(None, "tp", "fsdp"),
        })
    return rules


def param_shardings(config: TransformerConfig, mesh: Mesh, shapes=None):
    rules = sharding_rules(config)

    def walk(tree, prefix=""):
        if isinstance(tree, dict):
            return {
                name: walk(sub, f"{prefix}/{name}" if prefix else name)
                for name, sub in tree.items()
            }
        return NamedSharding(mesh, rules[prefix])

    if shapes is None:
        shapes = jax.eval_shape(
            functools.partial(init_params, config), jax.random.key(0)
        )
    return walk(shapes)


def _rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embeddings; x [b, s, heads, head_dim]."""
    half = x.shape[-1] // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions[:, :, None, None].astype(jnp.float32) * freqs
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    x1, x2 = x[..., :half], x[..., half:]
    rotated = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    )
    return rotated.astype(x.dtype)


def _attention_block(config: TransformerConfig, layer, x, positions):
    b, s, d = x.shape
    h, kv, hd = config.n_heads, config.n_kv_heads, config.head_dim
    normed = rms_norm(x, layer["attn_norm"])
    q = (normed @ dq(layer["wq"], x.dtype)).reshape(b, s, h, hd)
    k = (normed @ dq(layer["wk"], x.dtype)).reshape(b, s, kv, hd)
    v = (normed @ dq(layer["wv"], x.dtype)).reshape(b, s, kv, hd)
    q = _rope(q, positions, config.rope_theta)
    k = _rope(k, positions, config.rope_theta)
    if kv != h:
        reps = h // kv
        k = jnp.repeat(k, reps, axis=2)
        v = jnp.repeat(v, reps, axis=2)
    # [b, heads, s, hd] layout for the kernels
    q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
    if config.use_ring_attention:
        from dcos_commons_tpu.parallel.ring import ring_attention

        attn = ring_attention(q, k, v, axis_name=config.sp_axis, causal=True)
    elif config.use_ulysses_attention:
        from dcos_commons_tpu.parallel.ulysses import ulysses_attention

        attn = ulysses_attention(
            q, k, v, axis_name=config.sp_axis, causal=True,
            block_q=config.attn_block_q, block_k=config.attn_block_k,
        )
    else:
        attn = flash_attention(
            q, k, v, causal=True,
            block_q=config.attn_block_q, block_k=config.attn_block_k,
        )
    if config.remat and config.remat_policy == "save-attn":
        from jax.ad_checkpoint import checkpoint_name

        attn = checkpoint_name(attn, "attn_out")
    attn = attn.transpose(0, 2, 1, 3).reshape(b, s, h * hd)
    return x + attn @ dq(layer["wo"], x.dtype)


def _mlp_block(layer, x):
    normed = rms_norm(x, layer["mlp_norm"])
    gate = jax.nn.silu(normed @ dq(layer["w_gate"], x.dtype))
    up = normed @ dq(layer["w_up"], x.dtype)
    return x + (gate * up) @ dq(layer["w_down"], x.dtype)


def _ffn_block(config: TransformerConfig, layer, x, decode: bool = False):
    """The per-layer FFN: dense SwiGLU or MoE.  Returns (x, aux).

    MoE notes: tokens route in groups of <= moe_group_size (bounding
    the one-hot dispatch tensors; groups never span batch rows, so the
    slot cumsum stays within a dp shard).  In ``decode`` the capacity
    covers every token of the step — token dropping is a training-time
    load-balancing pressure; a server must not drop, and drop-free
    routing is also what makes cached decode equal full forwards."""
    if config.n_experts <= 0:
        return _mlp_block(layer, x), jnp.zeros((), jnp.float32)
    from dcos_commons_tpu.models.moe import MoEConfig, moe_ffn

    b, s, d = x.shape
    moe_config = MoEConfig(
        d_model=d,
        d_ff=config.d_ff,
        n_experts=config.n_experts,
        top_k=config.moe_top_k,
        capacity_factor=config.moe_capacity_factor,
        dtype=config.dtype,
    )
    moe_params = {
        key: layer[key] for key in ("router", "w_gate", "w_up", "w_down")
    }
    normed = rms_norm(x, layer["mlp_norm"])
    # group = a whole number of sequence positions per batch row so
    # groups never straddle rows; fall back to one row per group
    group = s if s <= config.moe_group_size else (
        config.moe_group_size if s % config.moe_group_size == 0 else s
    )
    tokens = normed.reshape(b * s // group, group, d)
    capacity = group if decode else None
    # axis_name=None: under jit, GSPMD partitions the expert einsums
    # from the param shardings (expert axis over ep) and inserts the
    # dispatch collectives — the shard_map path stays available for
    # explicit all_to_all control (dryrun's ep section)
    y, aux = jax.vmap(
        lambda g: moe_ffn(
            moe_config, moe_params, g, capacity=capacity,
            impl=config.moe_impl,
        )
    )(tokens)
    return x + y.reshape(b, s, d), aux.mean()


def _layer_scan(config: TransformerConfig, layers, x, positions):
    """Run x through a (sub)stack of layers with lax.scan.

    Mixed remat (``no_remat_layers`` = k > 0): the LAST k layers scan
    WITHOUT jax.checkpoint, storing their activations instead of
    recomputing them in backward.  Full-layer remat costs a whole
    extra forward (2NP FLOPs, ~24% of the train step at the flagship
    size); every layer that fits its activations in leftover HBM buys
    that fraction of the recompute back.  The non-remat span is the
    tail because those activations die first in backward."""

    def layer_fn(x, layer):
        x = _attention_block(config, layer, x, positions)
        x, aux = _ffn_block(config, layer, x)
        return x, aux

    remat_fn = layer_fn
    if config.remat:
        if config.remat_policy == "save-attn":
            from jax.ad_checkpoint import checkpoint_policies

            remat_fn = jax.checkpoint(
                layer_fn,
                policy=checkpoint_policies.save_only_these_names(
                    "attn_out"
                ),
            )
        else:
            remat_fn = jax.checkpoint(layer_fn)
    k = config.no_remat_layers if config.remat else 0
    if k <= 0:
        x, aux = lax.scan(remat_fn, x, layers)
        return x, aux.sum()
    n_layers = jax.tree.leaves(layers)[0].shape[0]
    k = min(k, n_layers)
    head = jax.tree.map(lambda a: a[: n_layers - k], layers)
    tail = jax.tree.map(lambda a: a[n_layers - k:], layers)
    aux_total = jnp.zeros((), jnp.float32)
    if n_layers - k > 0:
        x, aux = lax.scan(remat_fn, x, head)
        aux_total = aux_total + aux.sum()
    x, aux = lax.scan(layer_fn, x, tail)
    return x, aux_total + aux.sum()


def _logits(config: TransformerConfig, params: Params, x: jax.Array) -> jax.Array:
    x = rms_norm(x, params["final_norm"])
    # tied embeddings; f32 logits for a stable softmax
    return jnp.einsum(
        "bsd,vd->bsv", x.astype(jnp.float32),
        params["embed"].astype(jnp.float32),
    )


def _nll(logits: jax.Array, targets: jax.Array) -> jax.Array:
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]


def forward(
    config: TransformerConfig,
    params: Params,
    tokens: jax.Array,
    positions: Optional[jax.Array] = None,
) -> jax.Array:
    """tokens [b, s] -> logits [b, s, vocab] (f32)."""
    x, _aux = _trunk(config, params, tokens, positions)
    return _logits(config, params, x)


def _trunk(
    config: TransformerConfig,
    params: Params,
    tokens: jax.Array,
    positions: Optional[jax.Array] = None,
):
    """tokens [b, s] -> (final hidden states [b, s, d], moe aux)."""
    b, s = tokens.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
        if config.use_ring_attention or config.use_ulysses_attention:
            # each sp shard holds a consecutive chunk; RoPE needs the
            # GLOBAL position of every token, so offset by the shard
            idx = lax.axis_index(config.sp_axis)
            positions = positions + idx * s
    x = params["embed"][tokens].astype(config.dtype)
    return _layer_scan(config, params["layers"], x, positions)


def _nll_mean(
    config: TransformerConfig,
    params: Params,
    x: jax.Array,
    targets: jax.Array,
) -> jax.Array:
    """Mean NLL over [b, s] positions from final hidden states.

    With ``loss_chunk`` set, scans the sequence in chunks so only
    [b, chunk, vocab] f32 logits are ever live; jax.checkpoint makes
    the backward recompute each chunk instead of saving it — the same
    FLOPs-for-HBM trade the layer remat makes.
    """
    b, s, _ = x.shape
    chunk = config.loss_chunk
    if chunk <= 0 or s % chunk != 0 or s == chunk:
        return _nll(_logits(config, params, x), targets).mean()
    n_chunks = s // chunk
    xs = x.reshape(b, n_chunks, chunk, -1).swapaxes(0, 1)
    ts = targets.reshape(b, n_chunks, chunk).swapaxes(0, 1)

    def chunk_sum(total, operand):
        xc, tc = operand
        return total + _nll(_logits(config, params, xc), tc).sum(), None

    total, _ = lax.scan(jax.checkpoint(chunk_sum), jnp.zeros((), jnp.float32),
                        (xs, ts))
    return total / (b * s)


def loss_fn(
    config: TransformerConfig, params: Params, tokens: jax.Array,
    targets: jax.Array,
) -> jax.Array:
    x, aux = _trunk(config, params, tokens)
    loss = _nll_mean(config, params, x, targets)
    if config.n_experts > 0:
        # switch-transformer load-balancing term, averaged per layer
        loss = loss + config.moe_aux_weight * aux / config.n_layers
    return loss


def _pipeline_trunk(
    config: TransformerConfig,
    params: Params,
    tokens: jax.Array,
    n_micro: int,
    axis_name: str,
) -> jax.Array:
    """Embed + pipelined layer stack.  Returns microbatched
    activations [n_micro, mb, s, d] — valid on the LAST pp rank only.
    """
    if config.n_experts > 0:
        raise NotImplementedError(
            "MoE layers are not pipelined yet: run ep x dp/fsdp/tp "
            "meshes for the MoE flagship"
        )
    b, s = tokens.shape
    mb = b // n_micro
    positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (mb, s))
    x = params["embed"][tokens].astype(config.dtype)
    micro = split_microbatches(x, n_micro)
    stage_fn = lambda layers, x: _layer_scan(config, layers, x, positions)[0]
    return pipeline_apply(stage_fn, params["layers"], micro, axis_name)


def pipeline_forward(
    config: TransformerConfig,
    params: Params,
    tokens: jax.Array,
    n_micro: int,
    axis_name: str = "pp",
) -> jax.Array:
    """Forward with the layer trunk pipelined over the ``pp`` axis.

    Call inside shard_map with ``axis_name`` bound.  ``params`` holds
    this rank's stage: every ``layers`` leaf carries only the local
    n_layers/pp slice of the stack (shard the leading axis over pp);
    embed/final_norm are replicated and computed identically on every
    rank.  Batch is split into ``n_micro`` GPipe microbatches.
    Returns replicated logits (an activation-sized psum — prefer
    :func:`pipeline_loss_fn` for training, which only psums a scalar).
    """
    out = _pipeline_trunk(config, params, tokens, n_micro, axis_name)
    out = last_stage_value(out, axis_name)
    return _logits(config, params, merge_microbatches(out))


def pipeline_loss_fn(
    config: TransformerConfig,
    params: Params,
    tokens: jax.Array,
    targets: jax.Array,
    n_micro: int,
    axis_name: str = "pp",
) -> jax.Array:
    """Mean NLL, replicated over pp ranks.

    The vocab logits matmul + softmax run ONLY on the last pp rank
    (a runtime branch on the rank index); the cross-rank collective is
    a single scalar psum, not an activation broadcast.
    """
    out = _pipeline_trunk(config, params, tokens, n_micro, axis_name)
    x = merge_microbatches(out)
    idx = lax.axis_index(axis_name)
    n = axis_size(axis_name)

    def last_rank_loss(operands):
        params, x, targets = operands
        return _nll(_logits(config, params, x), targets).mean()

    loss_local = lax.cond(
        idx == n - 1,
        last_rank_loss,
        lambda operands: jnp.zeros((), jnp.float32),
        (params, x, targets),
    )
    return lax.psum(loss_local, axis_name)


def pipeline_param_specs(params_or_shapes) -> Dict[str, Any]:
    """PartitionSpec tree for pp sharding: layer stacks split on the
    leading axis, everything else replicated (shard_map in_specs)."""
    from jax.sharding import PartitionSpec as P

    def walk(tree, under_layers=False):
        if isinstance(tree, dict):
            return {
                name: walk(sub, under_layers or name == "layers")
                for name, sub in tree.items()
            }
        return P("pp") if under_layers else P()

    return walk(params_or_shapes)


def make_train_step(
    config: TransformerConfig,
    optimizer,
    mesh: Optional[Mesh] = None,
    donate: bool = True,
    grad_accum: int = 1,
):
    """Build a jitted (params, opt_state, tokens, targets) ->
    (params, opt_state, loss) step.

    With a mesh, in/out shardings pin params to the rule layout and
    batch to (dp, fsdp) x sp; XLA inserts the dp/fsdp gradient
    reduce-scatters and tp activation collectives.

    ``grad_accum`` > 1 splits the batch into that many microbatches
    and accumulates gradients over a ``lax.scan`` before the single
    optimizer update.  Numerics: equal-size splits make the mean of
    per-microbatch mean-losses (and gradients) EQUAL to the full-batch
    mean up to float reassociation — accumulation runs in f32 so k
    bf16 partial sums don't eat mantissa.  Perf: each microbatch's
    dp/fsdp reduce-scatter contributions become scan-carried partial
    sums, so XLA's latency-hiding scheduler can overlap microbatch
    i's ICI/DCN traffic with microbatch i+1's compute instead of
    serializing one giant gradient exchange behind the whole backward
    (megatron/alpa overlap discipline); remat (``config.remat``)
    composes per microbatch, shrinking live activations by the same
    factor.
    """
    grad_accum = max(1, int(grad_accum))

    def grads_of(params, tokens, targets):
        return jax.value_and_grad(
            lambda p: loss_fn(config, p, tokens, targets)
        )(params)

    def accumulate(params, tokens, targets):
        micro = (
            split_microbatches(tokens, grad_accum),
            split_microbatches(targets, grad_accum),
        )

        def one_microbatch(carry, mb):
            loss_sum, grad_sum = carry
            loss, grads = grads_of(params, *mb)
            grad_sum = jax.tree.map(
                lambda acc, g: acc + g.astype(jnp.float32),
                grad_sum, grads,
            )
            return (loss_sum + loss, grad_sum), None

        zeros = jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params
        )
        (loss_sum, grad_sum), _ = lax.scan(
            one_microbatch, (jnp.zeros((), jnp.float32), zeros), micro
        )
        grads = jax.tree.map(
            lambda p, g: (g / grad_accum).astype(p.dtype), params,
            grad_sum,
        )
        return loss_sum / grad_accum, grads

    def step(params, opt_state, tokens, targets):
        if grad_accum == 1:
            loss, grads = grads_of(params, tokens, targets)
        else:
            loss, grads = accumulate(params, tokens, targets)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree.map(
            lambda p, u: (p + u.astype(p.dtype)), params, updates
        )
        return params, opt_state, loss

    if mesh is None:
        return jax.jit(step, donate_argnums=(0, 1) if donate else ())

    from dcos_commons_tpu.parallel.mesh import batch_spec, replicated as rep

    params_shapes = jax.eval_shape(
        functools.partial(init_params, config), jax.random.key(0)
    )
    p_shard = param_shardings(config, mesh, params_shapes)
    opt_shapes = jax.eval_shape(optimizer.init, params_shapes)
    batch_sharding = NamedSharding(mesh, batch_spec())
    replicated = NamedSharding(mesh, rep())

    # optimizer state shardings: any leaf shaped like a param (whose
    # path ends with that param's path) inherits the param's sharding;
    # everything else (adam counts, scalars) is replicated
    def path_key(path):
        return tuple(
            str(getattr(k, "key", getattr(k, "idx", "?"))) for k in path
        )

    flat_params = {
        path_key(path): leaf.shape
        for path, leaf in jax.tree_util.tree_flatten_with_path(params_shapes)[0]
    }
    flat_pshard = {
        path_key(path): leaf
        for path, leaf in jax.tree_util.tree_flatten_with_path(p_shard)[0]
    }

    def opt_leaf_sharding(path, leaf):
        for ppath, pshape in flat_params.items():
            if leaf.shape == pshape and path[-len(ppath):] == ppath:
                return flat_pshard[ppath]
        return replicated

    opt_shard = jax.tree_util.tree_map_with_path(
        lambda path, leaf: opt_leaf_sharding(path_key(path), leaf),
        opt_shapes,
    )
    return jax.jit(
        step,
        in_shardings=(p_shard, opt_shard, batch_sharding, batch_sharding),
        out_shardings=(p_shard, opt_shard, replicated),
        donate_argnums=(0, 1) if donate else (),
    )
