"""HA control plane: election, fencing, re-hydration, chaos matrix.

The acceptance criteria of the HA subsystem (ISSUE 8):

* split-brain impossible by construction AND by test — a deposed
  leader holding a stale lease epoch gets its store mutations
  rejected (the two-scheduler race test below);
* the chaos matrix — the scheduler killed at every traceview
  span-boundary kind during a gang deploy — converges with no
  double-reservation, no orphaned launch, and no completed step
  re-run, with WAL/status reconciliation asserted per kill point.

Fast, fully-deterministic FakeAgent variants run in tier-1; the
real-process (LocalProcessAgent) matrix runs in the chaos/slow tier.
Replays: CHAOS_SEED=<seed from the failure log> reruns the identical
schedule.
"""

import os

import pytest

from dcos_commons_tpu.common import TaskState, TaskStatus
from dcos_commons_tpu.ha.election import (
    FencedPersister,
    HAState,
    LeaderLease,
    LeaderLock,
    LeaseFencedError,
    read_lease,
)
from dcos_commons_tpu.http.api import SchedulerApi
from dcos_commons_tpu.offer.inventory import SliceInventory, TpuHost
from dcos_commons_tpu.scheduler.builder import SchedulerBuilder
from dcos_commons_tpu.scheduler.config import SchedulerConfig
from dcos_commons_tpu.specification.yaml_spec import from_yaml
from dcos_commons_tpu.storage import MemPersister
from dcos_commons_tpu.testing import FakeAgent
from dcos_commons_tpu.testing.chaos import (
    CHAOS_KINDS,
    AutoChaosMatrix,
    ChaosHarness,
    ChaosMatrix,
    KillPoint,
    PersisterCrashProxy,
    auto_chaos_points,
    point_key,
)

CHAOS_SEED = int(os.environ.get("CHAOS_SEED", "0"))


@pytest.fixture(scope="module", autouse=True)
def _racecheck_probes():
    """Dynamic race probes (SDKLINT_RACECHECK=1): failover drives the
    scheduler cycle and the async checkpoint writer concurrently —
    watch their shared-write sets so any unordered pair fails the run.
    No-op in the fast tier."""
    from dcos_commons_tpu.scheduler.scheduler import DefaultScheduler
    from dcos_commons_tpu.utils.checkpoint import AsyncCheckpointer

    from conftest import racecheck_watch_guard

    yield from racecheck_watch_guard(DefaultScheduler, AsyncCheckpointer)


class FakeClock:
    def __init__(self, now: float = 1000.0):
        self.now = now

    def __call__(self) -> float:
        return self.now

    def advance(self, seconds: float) -> None:
        self.now += seconds


# -- election.py unit behavior ----------------------------------------


def test_lease_acquire_renew_takeover_epochs():
    clock = FakeClock()
    persister = MemPersister()
    a = LeaderLease(persister, "svc", "sched-a", ttl_s=10, clock=clock)
    assert a.try_acquire()
    assert a.is_leader and a.epoch == 1
    b = LeaderLease(persister, "svc", "sched-b", ttl_s=10, clock=clock)
    assert not b.try_acquire()  # lease live: candidate waits

    clock.advance(5.0)
    assert a.renew()
    assert a.epoch == 1  # renewal by the holder keeps the epoch
    clock.advance(8.0)
    assert not b.try_acquire()  # the renewal extended the lease

    clock.advance(8.0)  # now past a's last renewal + TTL
    assert b.try_acquire()
    assert b.epoch == 2 and b.takeovers == 1  # takeover mints epoch+1

    lost = []
    a.on_lost = lost.append
    assert a.renew() is False  # deposed: never silently re-takes
    assert lost and not a.is_leader

    # resign keeps the epoch but expires immediately: the successor
    # takes over without waiting out the TTL, at epoch+1
    b.resign()
    assert not b.is_leader
    c = LeaderLease(persister, "svc", "sched-c", ttl_s=10, clock=clock)
    assert c.try_acquire()
    assert c.epoch == 3

    record = read_lease(persister, "svc")
    assert record.owner == "sched-c" and record.epoch == 3


def test_fenced_persister_rejects_deposed_writer():
    clock = FakeClock()
    persister = MemPersister()
    a = LeaderLease(persister, "svc", "sched-a", ttl_s=5, clock=clock)
    assert a.try_acquire()
    fenced_a = FencedPersister(persister, a)
    fenced_a.set("/svc/x", b"from-a")
    assert fenced_a.get("/svc/x") == b"from-a"

    clock.advance(6.0)  # a stalls past its TTL
    b = LeaderLease(persister, "svc", "sched-b", ttl_s=5, clock=clock)
    assert b.try_acquire()

    lost = []
    a.on_lost = lost.append
    with pytest.raises(LeaseFencedError):
        fenced_a.set("/svc/x", b"from-a-after-deposition")
    assert persister.get("/svc/x") == b"from-a"  # the write never landed
    assert fenced_a.rejected_writes == 1
    assert lost  # fencing also fires the loss callback
    # a deposed leader may still OBSERVE (reads are unfenced)
    assert fenced_a.get("/svc/x") == b"from-a"
    # ...and the new leader writes normally
    fenced_b = FencedPersister(persister, b)
    fenced_b.set("/svc/x", b"from-b")
    assert persister.get("/svc/x") == b"from-b"
    with pytest.raises(LeaseFencedError):
        fenced_a.apply([])
    with pytest.raises(LeaseFencedError):
        fenced_a.recursive_delete("/svc/x")


def test_leader_lock_candidates_until_expiry():
    """LeaderLock.acquire blocks as a CANDIDATE and wins after the
    holder dies (no resign — the TTL does the work)."""
    import threading

    persister = MemPersister()
    holder = LeaderLock(persister, "svc", "sched-a", ttl_s=0.4)
    assert holder.acquire()
    candidate = LeaderLock(persister, "svc", "sched-b", ttl_s=0.4)
    won = threading.Event()

    def run():
        if candidate.acquire():
            won.set()

    thread = threading.Thread(target=run, daemon=True)
    thread.start()
    assert not won.wait(0.2)  # holder alive: still candidating
    holder.abort()  # SIGKILL analogue: renewals stop, no resign
    assert won.wait(5.0), "candidate never took over after TTL expiry"
    assert candidate.lease.epoch == 2
    candidate.release()
    holder_state = read_lease(persister, "svc")
    assert holder_state.owner == ""  # clean release resigned


def test_ha_uninstall_wipe_spares_the_lease():
    """A standalone uninstall wipes the whole tree THROUGH the fenced
    persister; deleting its own lease subtree mid-wipe would fence
    every remaining delete and wedge the uninstall forever —
    wipe_namespace must spare /__ha__ (the lease expires on its own)."""
    from dcos_commons_tpu.storage.persister import wipe_namespace

    clock = FakeClock()
    persister = MemPersister()
    lease = LeaderLease(persister, "svc", "sched-a", ttl_s=30, clock=clock)
    assert lease.try_acquire()
    fenced = FencedPersister(persister, lease)
    fenced.set("/svc/x", b"1")
    fenced.set("/other/y", b"2")
    wipe_namespace(fenced)  # standalone: wipe everything we own
    assert persister.get_or_none("/svc/x") is None
    assert persister.get_or_none("/other/y") is None
    assert read_lease(persister, "svc").owner == "sched-a"
    fenced.set("/post-wipe", b"1")  # still leader, still writable
    assert fenced.rejected_writes == 0


# -- the two-scheduler split-brain race (acceptance) ------------------


SERIAL_YAML = """
name: hasvc
pods:
  app:
    count: {count}
    placement: 'max-per-host:1'
    tasks:
      server:
        goal: RUNNING
        cmd: "sleep 60"
        cpus: 0.1
        memory: 32
"""


def _build_world(persister, agent, lease=None, count=2):
    builder = SchedulerBuilder(
        from_yaml(SERIAL_YAML.format(count=count)),
        SchedulerConfig(backoff_enabled=False, revive_capacity=10**9),
        persister,
    )
    builder.set_inventory(SliceInventory([
        TpuHost(host_id=f"host-{i}") for i in range(count)
    ]))
    builder.set_agent(agent)
    if lease is not None:
        builder.set_leader_lease(lease)
    return builder.build()


def _ack_running(agent, acked):
    for info in list(agent.launched):
        if info.task_id not in acked:
            acked.add(info.task_id)
            agent.send(TaskStatus(
                task_id=info.task_id, state=TaskState.RUNNING,
                ready=True, agent_id=info.agent_id,
            ))


def test_two_scheduler_race_rejects_deposed_leader_writes():
    """THE split-brain test: scheduler A deploys as leader, stalls
    past its TTL; standby B takes the lease (epoch+1) and finishes the
    rollout.  Every store mutation A attempts after deposition — both
    a direct store write and a full scheduler cycle — is REJECTED by
    the fenced write path; the persisted tree is exactly B's."""
    clock = FakeClock()
    persister = MemPersister()
    agent = FakeAgent()
    acked: set = set()

    lease_a = LeaderLease(persister, "hasvc", "sched-a", ttl_s=10,
                          clock=clock)
    assert lease_a.try_acquire()
    sched_a = _build_world(persister, agent, lease_a)

    # A deploys the first pod, then stalls mid-rollout
    sched_a.run_cycle()
    _ack_running(agent, acked)
    sched_a.run_cycle()
    assert agent.launched, "A never launched anything"
    assert not sched_a.deploy_manager.get_plan().is_complete

    clock.advance(11.0)  # A's lease expires un-renewed
    lease_b = LeaderLease(persister, "hasvc", "sched-b", ttl_s=10,
                          clock=clock)
    assert lease_b.try_acquire()
    assert lease_b.epoch == lease_a.epoch + 1
    sched_b = _build_world(persister, agent, lease_b)

    # a queued status makes A's next cycle attempt a store mutation:
    # the fence rejects it and the cycle FAILS (crash-to-restart)
    _ack_running(agent, acked)
    with pytest.raises(LeaseFencedError):
        sched_a.run_cycle()
    with pytest.raises(LeaseFencedError):
        sched_a.state_store.store_property("a-was-here", b"1")
    assert sched_a.ha_state is not None
    assert not sched_a.ha_state.lease.is_leader

    # the status A consumed never persisted; the FakeAgent is
    # edge-triggered, so replay it for B (a real agent keeps
    # reporting until the status is acted on)
    acked.clear()
    _ack_running(agent, acked)
    for _ in range(12):
        sched_b.run_cycle()
        _ack_running(agent, acked)
        if sched_b.deploy_manager.get_plan().is_complete:
            break
    assert sched_b.deploy_manager.get_plan().is_complete
    # B's incarnation adopted A's live launches instead of redoing them
    assert sched_b.last_rehydration["adopted"] >= 1
    # the tree carries no deposed-leader writes
    assert sched_b.state_store.fetch_property("a-was-here") is None
    assert sched_a.ha_state.describe(refresh=False)[
        "fenced_writes_rejected"] >= 2


# -- re-hydration: operator state survives restart --------------------


def test_rehydrate_restores_interrupt_and_force_complete():
    """A restarted scheduler used to forget operator verbs: an
    interrupted rollout silently resumed, a forced-complete step went
    back to PENDING.  The plan checkpoint restores both."""
    persister = MemPersister()
    agent = FakeAgent()
    acked: set = set()
    # 3 pods so app-2 stays PENDING: the deploy must remain incomplete
    # across the restart (a completed deploy rebuilds as "update")
    sched1 = _build_world(persister, agent, count=3)
    api = SchedulerApi(sched1)

    sched1.run_cycle()
    _ack_running(agent, acked)
    sched1.run_cycle()  # app-0 COMPLETE, app-1 launching next
    api.plan_interrupt("deploy")
    code, _body = api.plan_force_complete(
        "deploy", "app", "app-1:[server]"
    )
    assert code == 200
    sched1.run_cycle()  # checkpoint written

    sched2 = _build_world(persister, agent, count=3)
    sched2.run_cycle()  # rehydration restores the checkpoint
    plan = sched2.plan("deploy")
    assert plan.is_interrupted(), "operator interrupt lost in restart"
    step = plan.step("app", "app-1:[server]")
    assert step is not None and step.get_status().is_complete, (
        "force-complete lost in restart"
    )
    pending = plan.step("app", "app-2:[server]")
    assert pending is not None and pending.is_pending
    assert sched2.last_rehydration["restored_plans"] >= 1
    assert sched2.last_rehydration["restored_steps"] >= 1


def test_rehydrate_never_regresses_completed_steps():
    """A checkpoint that PREDATES the statuses completing a step must
    not pull the step back: restore only moves steps forward."""
    persister = MemPersister()
    agent = FakeAgent()
    acked: set = set()
    sched1 = _build_world(persister, agent)
    sched1.run_cycle()  # launches app-0; checkpoint says STARTING
    _ack_running(agent, acked)  # RUNNING persisted, but NO cycle ran:
    # the checkpoint still says STARTING when the scheduler "dies"
    sched2 = _build_world(persister, agent)
    sched2.run_cycle()
    step = sched2.plan("deploy").step("app", "app-0:[server]")
    assert step.get_status().is_complete, (
        "stale checkpoint regressed a completed step"
    )


# -- the chaos kill matrix (fast tier: FakeAgent) ---------------------


@pytest.mark.parametrize("kind", CHAOS_KINDS)
def test_chaos_single_kill_converges(kind):
    """Kill the scheduler once at each span-boundary kind during the
    4-host gang deploy; the successor converges and the per-kill-point
    WAL/status reconciliation is exactly what the persisted state at
    death implies."""
    harness = ChaosHarness(seed=CHAOS_SEED)
    try:
        report = harness.run(KillPoint(kind, 1), timeout_s=30)
    finally:
        harness.shutdown()
    assert report.killed and report.converged and \
        report.incarnations == 2, report.describe()
    rehydration = report.rehydration
    assert rehydration is not None, report.describe()
    if kind == "post-evaluate":
        # nothing was persisted: the successor re-evaluates cleanly
        assert rehydration["reissued"] == 0 and \
            rehydration["adopted"] == 0, report.describe()
    elif kind == "post-wal":
        # WAL'd but never launched: the successor re-issues it
        assert rehydration["reissued"] >= 1, report.describe()
        for name, staging_id in report.prekill_staging_ids.items():
            assert report.final_task_ids.get(name) != staging_id, (
                f"{name} kept the never-launched id: "
                f"{report.describe()}"
            )
    else:
        # the launch reached the agent before death: adopt, never redo
        assert rehydration["adopted"] >= 1 and \
            rehydration["reissued"] == 0, report.describe()
    assert rehydration["double_reservations"] == 0


def test_chaos_kill_during_gang_rollout_preserves_completed_ctl():
    """Occurrence 2 targets the trainer GANG's rollout: the already-
    COMPLETE ctl step must ride through the failover untouched (no
    completed-step re-run), while the gang converges."""
    harness = ChaosHarness(seed=CHAOS_SEED)
    try:
        report = harness.run(KillPoint("post-wal", 2), timeout_s=30)
    finally:
        harness.shutdown()
    assert report.killed and report.converged, report.describe()
    assert ("deploy", "ctl", "ctl-0:[server]") in \
        report.prekill_complete_steps, report.describe()
    # the gang's 4 WAL'd-but-unlaunched workers were all re-issued
    assert report.rehydration["reissued"] == 4, report.describe()


def test_chaos_unkilled_baseline():
    """The harness's invariants hold trivially with no kill (guards
    against the invariants passing vacuously)."""
    harness = ChaosHarness(seed=CHAOS_SEED)
    try:
        report = harness.run(None, timeout_s=30)
    finally:
        harness.shutdown()
    assert report.converged and not report.killed
    assert report.incarnations == 1


# -- the auto-derived chaos matrix (durcheck persistence points) ------


def test_auto_chaos_matrix_outgrows_hand_wired_kinds():
    """The statically derived matrix: durcheck's persistence-point map
    yields strictly more crash boundaries than the 5 hand-wired span
    kinds, every budgeted crash run converges with ZERO unWAL'd
    effects at death (crash-before-mutation is the maximal window),
    and every discovered boundary is accounted as reached or
    unreached — never silently skipped."""
    matrix = AutoChaosMatrix(seed=CHAOS_SEED, budget=6)
    assert len(matrix.points) > len(CHAOS_KINDS), (
        "static discovery found fewer boundaries than the hand-wired "
        f"kinds: {len(matrix.points)}"
    )
    result = matrix.run(lambda seed: ChaosHarness(seed=seed),
                        timeout_s=30)
    # discovery beats the hand-wired matrix on REACHED (not just
    # discovered) boundaries
    assert len(result.reached) > len(CHAOS_KINDS), result.describe()
    # full accounting: reached and unreached partition the point set
    reached = {point_key(p) for p in result.reached}
    unreached = {point_key(p) for p in result.unreached}
    assert not reached & unreached
    assert reached | unreached == {point_key(p) for p in result.all_points}
    # the budgeted subset all died at their boundary and converged,
    # and the healthy scheduler never leaks an effect past its WAL
    assert len(result.reports) == len(result.targeted) == \
        min(6, len(result.reached))
    for boundary in result.reports:
        assert boundary.report.killed and boundary.report.converged, \
            f"{boundary.point}: {boundary.report.describe()}"
        assert boundary.unwald_at_death == [], (
            f"unWAL'd effect at {boundary.point}: "
            f"{boundary.unwald_at_death}"
        )


def test_auto_chaos_seed_replays_identical_subset():
    """CHAOS_SEED=<seed> replays the exact budgeted subset: same seed,
    same targeted boundaries, in order (the CI budget discipline the
    failure-log replay instructions depend on)."""
    runs = []
    for _ in range(2):
        matrix = AutoChaosMatrix(seed=CHAOS_SEED, budget=2)
        result = matrix.run(lambda seed: ChaosHarness(seed=seed),
                            timeout_s=30)
        runs.append([point_key(p) for p in result.targeted])
    assert runs[0] == runs[1] and len(runs[0]) == 2


def test_seeded_unwald_launch_bug_caught_both_ways():
    """The seeded durability bug — launch reaches the agent BEFORE its
    WAL write — is caught twice over: statically by
    dur-effect-before-wal on a fixture of the same shape, and
    dynamically by a crashed auto boundary observing a nonzero
    unWAL'd-effect set at death."""
    # static half: same shape as the runtime bug below
    from dcos_commons_tpu.analysis import durcheck

    fixture = (
        "class BuggyRecorder:\n"
        "    def record(self, infos, parent=None):\n"
        "        self.agent.launch(infos)\n"
        "        self._state_store.store_launch(infos)\n"
    )
    static = durcheck.analyze_paths(
        [("/fix/rec.py", "dcos_commons_tpu/state/rec.py", fixture)]
    )
    assert [f.rule for f in static.findings] == ["dur-effect-before-wal"]
    assert "launch" in static.findings[0].message

    # dynamic half: crash at the store_launch boundary with the launch
    # effect moved ahead of the recorder's WAL write
    points = auto_chaos_points()
    target = next(
        p for p in points
        if str(p["file"]).endswith("state/state_store.py")
        and str(p["function"]).endswith("store_launch")
    )
    harness = ChaosHarness(seed=CHAOS_SEED)
    proxy = PersisterCrashProxy(harness.persister, points, target=target)
    harness.persister = proxy
    try:
        scheduler = harness.build_scheduler()
        real_record = scheduler.launch_recorder.record

        def buggy_record(infos, parent=None):
            harness.agent.launch(infos)  # effect escapes its WAL
            real_record(infos, parent=parent)

        scheduler.launch_recorder.record = buggy_record
        boundary = harness.run_boundary(proxy, timeout_s=30)
    finally:
        harness.shutdown()
    report = boundary.report
    assert report.killed and report.converged, report.describe()
    # the dynamic signature of the static finding: agent-active tasks
    # the store had never heard of at the moment of death
    assert boundary.unwald_at_death, report.describe()


# -- the chaos kill matrix (chaos tier: real processes) ---------------


@pytest.mark.slow
@pytest.mark.chaos
def test_chaos_matrix_gang_deploy_local_processes(tmp_path):
    """THE acceptance matrix: a 4-host gang deploy through a REAL
    LocalProcessAgent (task processes survive scheduler death, exactly
    like production), the scheduler killed at every span-boundary kind
    x two occurrences (the ctl rollout and the gang rollout), every
    run converging under the full invariant set.  Failures replay
    with CHAOS_SEED=<seed> from the report in the assertion message."""
    matrix = ChaosMatrix(occurrences=(1, 2), seed=CHAOS_SEED)

    run_dirs = iter(range(10_000))

    def factory(seed):
        return ChaosHarness(
            workdir=str(tmp_path / f"agent-{next(run_dirs)}"),
            seed=seed,
            task_cmd="sleep 120",
        )

    reports = matrix.run(factory, timeout_s=120)
    assert len(reports) == len(CHAOS_KINDS) * 2
    for report in reports:
        assert report.killed and report.converged, report.describe()
        rehydration = report.rehydration
        assert rehydration is not None, report.describe()
        # WAL/status reconciliation per kill point: only a post-wal
        # death leaves a WAL'd-but-unlaunched task to re-issue
        if report.kill.kind == "post-wal":
            assert rehydration["reissued"] >= 1, report.describe()
        else:
            assert rehydration["reissued"] == 0, report.describe()
        assert rehydration["double_reservations"] == 0, report.describe()


# -- process-level failover e2e (serve --ha) --------------------------


HA_PROCESS_YAML = """
name: hasvc
pods:
  app:
    count: 3
    placement: 'max-per-host:1'
    tasks:
      server:
        goal: RUNNING
        cmd: "echo serving > out.txt && sleep 180"
        cpus: 0.1
        memory: 32
"""


@pytest.mark.slow
def test_serve_ha_standby_takes_over_on_leader_sigkill(tmp_path):
    """THE runner-level failover e2e: two real `serve --ha` scheduler
    processes against a real state server and real agent daemons.
    The standby BLOCKS as a candidate while the leader lives; the
    leader is SIGKILLed mid-deploy (with the plan interrupted, so the
    takeover provably resumes operator state); the standby takes the
    lease within ~TTL, re-hydrates — adopting the running task, not
    restarting it — restores the interrupt, and completes the rollout
    after `plan continue`."""
    import json as _json
    import urllib.request

    from dcos_commons_tpu.testing.integration import (
        AgentProcess,
        SchedulerProcess,
        _read_announce,
        reap_orphan_tasks,
        start_state_server,
    )

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    agents = [
        AgentProcess(f"h{i}", str(tmp_path / f"agent-{i}"), repo)
        for i in range(3)
    ]
    state = sched_a = sched_b = None
    state_log = None
    try:
        svc = tmp_path / "svc.yml"
        svc.write_text(HA_PROCESS_YAML)
        topology = tmp_path / "topology.yml"
        lines = ["hosts:"]
        for agent in agents:
            lines += [
                f"  - host_id: {agent.host_id}",
                f"    agent_url: {agent.url}",
                "    cpus: 4.0",
                "    memory_mb: 8192",
            ]
        topology.write_text("\n".join(lines) + "\n")
        state, state_url, state_log = start_state_server(
            str(tmp_path / "state"), repo
        )
        env = {"ENABLE_BACKOFF": "false", "STATE_LEASE_TTL_S": "2"}
        sched_a = SchedulerProcess(
            str(svc), str(topology), str(tmp_path / "sched-a"),
            env=env, repo_root=repo,
            extra_args=["--state-url", state_url, "--ha"],
        )
        # the standby parks in candidate acquire(): its API (and
        # announce file) only appear AFTER it wins the lease
        sched_b = SchedulerProcess(
            str(svc), str(topology), str(tmp_path / "sched-b"),
            env=env, repo_root=repo,
            extra_args=["--state-url", state_url, "--ha"],
            wait_listening=False,
        )
        client_a = sched_a.client()
        client_a.wait_for_task_state(
            "app-0-server", "TASK_RUNNING", timeout_s=60
        )
        running_ids = client_a.task_ids()
        client_a.post("/v1/plans/deploy/interrupt")
        assert client_a.plan_status("deploy") != "COMPLETE"
        body = client_a.get("/v1/debug/ha")
        assert body["is_leader"] is True and body["lease_epoch"] == 1
        assert sched_b.process.poll() is None, "standby exited early"

        sched_a.process.kill()  # SIGKILL: no resign, the TTL does it
        sched_a.process.wait(timeout=10)

        url_b = _read_announce(
            os.path.join(sched_b.workdir, "announce"), timeout_s=60
        )
        sched_b.url = url_b
        client_b = sched_b.client()
        # the API comes up before the loop's first cycle: poll until
        # the re-hydration pass has run
        from dcos_commons_tpu.testing.integration import wait_for

        body = wait_for(
            lambda: (lambda b: b if "last_rehydration" in b else None)(
                client_b.get("/v1/debug/ha")
            ),
            timeout_s=30, what="standby re-hydration",
        )
        assert body["is_leader"] is True
        assert body["lease_epoch"] == 2, body
        assert body["last_rehydration"]["adopted"] >= 1, body
        # the operator's interrupt survived the failover
        assert client_b.plan_status("deploy") in ("WAITING", "IN_PROGRESS")
        plan = client_b.get("/v1/plans/deploy")
        assert plan["status"] != "COMPLETE"
        client_b.post("/v1/plans/deploy/continue")
        client_b.wait_for_completed_deployment(timeout_s=120)
        # adopted, not restarted: the pre-failover task kept its id
        final_ids = client_b.task_ids()
        for name, task_id in running_ids.items():
            if task_id:
                assert final_ids.get(name) == task_id, (name, task_id)
        # a deposed leader never came back: exactly one claimant
        with urllib.request.urlopen(url_b + "/v1/metrics",
                                    timeout=10) as resp:
            metrics = _json.loads(resp.read())
        assert metrics["ha.is_leader"] == 1.0
        assert metrics["ha.failovers_total"] == 1.0
    finally:
        for sched in (sched_a, sched_b):
            if sched is not None:
                sched.terminate()
        reap_orphan_tasks(agents)
        for agent in agents:
            agent.stop()
        if state is not None and state.poll() is None:
            state.terminate()
            state.wait(timeout=10)
        if state_log is not None:
            state_log.close()


# -- observability: /v1/debug/ha + gauges + the failover chain --------


def test_debug_ha_route_and_gauges():
    clock = FakeClock()
    persister = MemPersister()
    lease = LeaderLease(persister, "hasvc", "sched-a", ttl_s=30,
                        clock=clock)
    assert lease.try_acquire()
    sched = _build_world(persister, FakeAgent(), lease)
    api = SchedulerApi(sched)

    code, body = api.debug_ha()
    assert code == 200 and body["enabled"] is True
    assert body["is_leader"] is True
    assert body["lease_epoch"] == 1
    assert body["leader"]["owner"] == "sched-a"
    assert body["leader"]["live"] is True
    assert 0 < body["leader"]["expires_in_s"] <= 30
    assert body["failovers_total"] == 0
    assert body["fenced_writes_rejected"] == 0

    sched.run_cycle()
    code, body = api.debug_ha()
    assert body["last_rehydration"]["adopted"] == 0

    snapshot = sched.metrics.snapshot()
    assert snapshot["ha.is_leader"] == 1.0
    assert snapshot["ha.lease_epoch"] == 1.0
    assert snapshot["ha.failovers_total"] == 0.0

    # the route is wired (not just the query method)
    from dcos_commons_tpu.http.server import build_routes

    patterns = [route[1].pattern for route in build_routes(api)]
    assert any("/v1/debug/ha" in p for p in patterns)

    # a scheduler without HA wiring reports disabled (never 500s)
    plain = _build_world(MemPersister(), FakeAgent())
    plain.run_cycle()
    code, body = SchedulerApi(plain).debug_ha()
    assert code == 200 and body["enabled"] is False
    assert "last_rehydration" in body


def test_failover_reads_as_one_correlation_chain():
    """election.promote -> rehydrate.replay share one trace id: the
    operator sees the takeover and what it replayed as ONE chain in
    /v1/debug/trace, both formats."""
    clock = FakeClock()
    persister = MemPersister()
    agent = FakeAgent()
    acked: set = set()

    lease_a = LeaderLease(persister, "hasvc", "sched-a", ttl_s=5,
                          clock=clock)
    assert lease_a.try_acquire()
    sched_a = _build_world(persister, agent, lease_a)
    sched_a.run_cycle()
    _ack_running(agent, acked)
    sched_a.run_cycle()

    clock.advance(6.0)
    lease_b = LeaderLease(persister, "hasvc", "sched-b", ttl_s=5,
                          clock=clock)
    assert lease_b.try_acquire()
    sched_b = _build_world(persister, agent, lease_b)
    sched_b.run_cycle()

    spans = sched_b.tracer.snapshot()
    promotes = [s for s in spans if s.name == "election.promote"]
    assert promotes, [s.name for s in spans]
    replays = [s for s in spans if s.name == "rehydrate.replay"]
    assert replays, [s.name for s in spans]
    assert replays[0].trace_id == promotes[-1].trace_id
    assert replays[0].parent_id == promotes[-1].span_id
    assert replays[0].attrs["adopted"] >= 1

    # a clean handover records its resign too
    lease_b.resign()
    assert any(
        s.name == "election.resign" for s in sched_b.tracer.snapshot()
    )

    # both export formats carry the chain
    from dcos_commons_tpu.trace.export import to_chrome, to_text

    text = to_text(sched_b.tracer, service="hasvc")
    assert "election.promote" in text and "rehydrate.replay" in text
    chrome = to_chrome(sched_b.tracer, service="hasvc")
    names = {e["name"] for e in chrome["traceEvents"]}
    assert "election.promote" in names and "rehydrate.replay" in names
    assert "election.resign" in names


def test_ha_state_replication_lag_gauges():
    """Against a real primary/standby state-server pair, HAState
    surfaces per-puller replication lag as gauges and standby
    watermarks in the /v1/debug/ha body."""
    from dcos_commons_tpu.metrics.registry import Metrics
    from dcos_commons_tpu.storage.remote import RemotePersister, StateServer

    primary = StateServer(MemPersister()).start()
    standby = StateServer(
        MemPersister(), replicate_from=primary.url
    ).start()
    try:
        client = RemotePersister(primary.url)
        client.set("/svc/a", b"1")
        import time as _time

        deadline = _time.monotonic() + 10
        while _time.monotonic() < deadline:
            status = client._call("/v1/repl/status", {})
            if status["standby_attached"] and not status["standby_lagging"]:
                break
            _time.sleep(0.05)
        ha = HAState(client, "hasvc")
        ha._metrics = Metrics()
        body = ha.describe(refresh=True)
        assert body["replication"]["role"] == "primary"
        assert body["replication"]["standbys"], body
        (puller_id, watermark), = body["replication"]["standbys"].items()
        assert watermark["lag"] == 0
        snapshot = ha._metrics.snapshot()
        assert snapshot[f"ha.replication.lag.{puller_id}"] == 0.0
    finally:
        standby.stop()
        primary.stop()
