"""HTTP client for the scheduler API.

Reference: cli/client/client.go — thin wrapper adding the service URL
prefix and surfacing non-2xx responses as errors with the body text.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Any, Optional
from urllib.parse import urlencode


class CliError(Exception):
    def __init__(self, code: int, body: Any):
        self.code = code
        self.body = body
        super().__init__(f"HTTP {code}: {body}")


class ApiClient:
    def __init__(self, base_url: str, timeout_s: float = 10.0):
        self._base = base_url.rstrip("/")
        self._timeout = timeout_s

    def get(self, path: str) -> Any:
        return self._request("GET", path)

    def post(self, path: str, params: Optional[dict] = None) -> Any:
        if params:
            clean = {k: v for k, v in params.items() if v is not None}
            if clean:
                path = f"{path}?{urlencode(clean, doseq=True)}"
        return self._request("POST", path)

    def _request(self, method: str, path: str) -> Any:
        request = urllib.request.Request(
            self._base + path, method=method,
            data=b"" if method == "POST" else None,
        )
        try:
            with urllib.request.urlopen(request, timeout=self._timeout) as resp:
                code, raw = resp.status, resp.read()
        except urllib.error.HTTPError as e:
            code, raw = e.code, e.read()
        except urllib.error.URLError as e:
            raise CliError(0, f"cannot reach scheduler at {self._base}: {e}")
        body = raw.decode("utf-8", errors="replace")
        try:
            body = json.loads(body)
        except json.JSONDecodeError:
            pass
        if code >= 400:
            raise CliError(code, body)
        return body
