"""PlanGenerator: the YAML ``plans:`` section -> Plan objects.

Reference: specification/PlanGenerator.java + yaml RawPlan/RawPhase
(specification/yaml/RawServiceSpec beans).  YAML shape:

    plans:
      deploy:
        strategy: serial
        phases:
          first-phase:
            strategy: parallel
            pod: hello
            steps:            # optional explicit per-instance steps
              - 0: [[task-a, task-b]]
              - 1: [[task-a]]

Without ``steps`` a phase covers every instance of the pod with every
task (gang pods: one step for the whole slice).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from dcos_commons_tpu.plan.backoff import Backoff
from dcos_commons_tpu.plan.builders import DeployPlanFactory
from dcos_commons_tpu.plan.phase import Phase
from dcos_commons_tpu.plan.plan import Plan
from dcos_commons_tpu.plan.step import DeploymentStep, PodInstanceRequirement
from dcos_commons_tpu.plan.strategy import strategy_for_name
from dcos_commons_tpu.specification.specs import ServiceSpec, SpecError
from dcos_commons_tpu.state.state_store import StateStore


def dependency_cycle(edges: Dict[str, List[str]]) -> Optional[List[str]]:
    """First cycle in a name -> prerequisites graph (as a closed node
    list), or None.  Shared by plan generation and the spec analyzer."""
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {name: WHITE for name in edges}
    path: List[str] = []

    def visit(name: str) -> Optional[List[str]]:
        color[name] = GRAY
        path.append(name)
        for dep in edges.get(name, ()):
            if color.get(dep, WHITE) == GRAY:
                return path[path.index(dep):] + [dep]
            if color.get(dep, WHITE) == WHITE and dep in edges:
                found = visit(dep)
                if found:
                    return found
        path.pop()
        color[name] = BLACK
        return None

    for name in sorted(edges):
        if color[name] == WHITE:
            found = visit(name)
            if found:
                return found
    return None


class PlanGenerator:
    def __init__(self, backoff: Optional[Backoff] = None):
        self._factory = DeployPlanFactory(backoff)
        self._backoff = backoff

    def generate(
        self,
        spec: ServiceSpec,
        plan_name: str,
        raw_plan: Dict[str, Any],
        state_store: StateStore,
        target_config_id: str,
    ) -> Plan:
        phases: List[Phase] = []
        phases_raw = raw_plan.get("phases") or {}
        for phase_name, raw_phase in phases_raw.items():
            phases.append(
                self._generate_phase(
                    spec, phase_name, raw_phase or {}, state_store, target_config_id
                )
            )
        # phase-level `dependencies: [other-phase, ...]` builds a DAG
        # plan (reference: DependencyStrategy/DependencyStrategyHelper)
        # instead of the flat serial/parallel strategies.  Unknown
        # names and cycles are CONFIG errors caught here (and by the
        # spec analyzer at lint time), never a silently-stuck plan.
        edges: Dict[str, List[str]] = {}
        for phase_name, raw_phase in phases_raw.items():
            deps = [str(d) for d in (raw_phase or {}).get("dependencies") or []]
            if deps:
                edges[str(phase_name)] = deps
        if edges:
            if "strategy" in raw_plan:
                # an explicit plan strategy AND a dependency DAG both
                # claim to order the phases; silently preferring one
                # would break whichever the YAML author believed in
                raise SpecError(
                    f"plan {plan_name!r}: 'strategy: "
                    f"{raw_plan['strategy']}' cannot be combined with "
                    "phase 'dependencies' (the DAG defines the order; "
                    "drop one)"
                )
            known = set(map(str, phases_raw))
            unknown = sorted(
                {d for deps in edges.values() for d in deps} - known
            )
            if unknown:
                raise SpecError(
                    f"plan {plan_name!r}: dependencies name unknown "
                    f"phase(s) {unknown} (have: {sorted(known)})"
                )
            cycle = dependency_cycle(edges)
            if cycle:
                raise SpecError(
                    f"plan {plan_name!r}: phase dependency cycle "
                    + " -> ".join(cycle)
                )
            from dcos_commons_tpu.plan.strategy import DependencyStrategy

            return Plan(plan_name, phases, DependencyStrategy(edges))
        return Plan(
            plan_name,
            phases,
            strategy_for_name(str(raw_plan.get("strategy", "serial"))),
        )

    def _generate_phase(
        self,
        spec: ServiceSpec,
        phase_name: str,
        raw_phase: Dict[str, Any],
        state_store: StateStore,
        target_config_id: str,
    ) -> Phase:
        pod_name = raw_phase.get("pod")
        if not pod_name:
            raise SpecError(f"phase {phase_name!r} requires a pod")
        pod = spec.pod(str(pod_name))
        strategy_name = str(raw_phase.get("strategy", "serial"))
        raw_steps = raw_phase.get("steps")
        if not raw_steps:
            return self._factory.build_phase(
                pod, state_store, target_config_id, strategy_name,
                phase_name=phase_name,
            )
        steps: List[DeploymentStep] = []
        # '- default: [[tasks]]' covers every instance not explicitly
        # listed (reference: cassandra svc.yml deploy steps use
        # 'default' to stay count-agnostic)
        explicit = set()
        expanded = []
        for entry in raw_steps:
            if not isinstance(entry, dict) or len(entry) != 1:
                raise SpecError(
                    f"phase {phase_name!r}: each step must be one "
                    "{index: [[tasks...]]} mapping"
                )
            ((raw_index, task_groups),) = entry.items()
            if str(raw_index) == "default":
                if any(i is None for i, _ in expanded):
                    raise SpecError(
                        f"phase {phase_name!r}: multiple 'default' step "
                        "entries would deploy the same instances twice"
                    )
                expanded.append((None, task_groups))
                continue
            try:
                index = int(raw_index)
            except (TypeError, ValueError):
                raise SpecError(
                    f"phase {phase_name!r}: step index {raw_index!r} "
                    "is not an integer or 'default'"
                )
            if not 0 <= index < pod.count:
                raise SpecError(
                    f"phase {phase_name!r}: step index {index} out of "
                    f"range for pod {pod.type!r} (count {pod.count})"
                )
            explicit.add(index)
            expanded.append((index, task_groups))
        flat: List = []
        for index, task_groups in expanded:
            if index is None:
                covered = [
                    i for i in range(pod.count) if i not in explicit
                ]
                if pod.gang:
                    # gang pods deploy slice-atomically: 'default' is
                    # ONE step over every covered instance (matching
                    # DeployPlanFactory's whole-slice step)
                    flat.append((covered, task_groups))
                else:
                    flat.extend((([i], task_groups)) for i in covered)
            else:
                flat.append(([index], task_groups))
        for instances, task_groups in flat:
            for tasks in task_groups:
                task_list = [str(t) for t in tasks]
                unknown = [
                    t for t in task_list
                    if t not in {s.name for s in pod.tasks}
                ]
                if unknown:
                    raise SpecError(
                        f"phase {phase_name!r}: unknown tasks {unknown} "
                        f"for pod {pod.type!r}"
                    )
                requirement = PodInstanceRequirement(
                    pod=pod, instances=list(instances),
                    tasks_to_launch=task_list,
                )
                label = (
                    f"{pod.type}-{instances[0]}"
                    if len(instances) == 1
                    else f"{pod.type}-gang"
                )
                step = DeploymentStep(
                    f"{label}:[{','.join(task_list)}]",
                    requirement,
                    backoff=self._backoff,
                )
                self._factory.seed_step_from_state(
                    step, pod, list(instances), state_store, target_config_id
                )
                steps.append(step)
        return Phase(phase_name, steps, strategy_for_name(strategy_name))
