"""Control-plane authentication: bearer token + TLS on every surface.

Reference posture: the reference authenticates every control-plane hop
(dcos/auth token providers, ServiceAccountIAMTokenClient, ZK ACLs in
CuratorPersister.java:43-110).  These tests prove the rebuild's
analogue: with a cluster token set, anonymous launch / kill / kv-set /
plan verbs are rejected with 401 on the scheduler API, the agent
daemons, and the state server — while the authenticated deploy /
recovery flow still works end to end across real processes.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

from dcos_commons_tpu.http import ApiServer
from dcos_commons_tpu.agent.daemon import AgentDaemon
from dcos_commons_tpu.security.auth import auth_headers, certs_main
from dcos_commons_tpu.storage.file_persister import FileWalPersister
from dcos_commons_tpu.storage.persister import PersisterError
from dcos_commons_tpu.storage.remote import (
    RemoteLocker,
    RemotePersister,
    StateServer,
)
from dcos_commons_tpu.testing import (
    AdvanceCycles,
    ExpectDeploymentComplete,
    SendTaskRunning,
    ServiceTestRunner,
)
from dcos_commons_tpu.testing.integration import (
    AgentProcess,
    SchedulerProcess,
    wait_for,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TOKEN = "test-cluster-token-0123456789abcdef"

YAML = """
name: authed
pods:
  web:
    count: 1
    tasks:
      srv:
        goal: RUNNING
        cmd: "serve"
        cpus: 0.1
        memory: 32
"""


def http(method, url, body=None, token="", expect=200):
    data = json.dumps(body).encode() if body is not None else (
        b"" if method in ("POST", "PUT") else None
    )
    req = urllib.request.Request(
        url, data=data, method=method, headers=auth_headers(token)
    )
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            code, raw = resp.status, resp.read()
    except urllib.error.HTTPError as e:
        code, raw = e.code, e.read()
    assert code == expect, f"{method} {url} -> {code}: {raw[:200]}"
    return json.loads(raw) if raw else None


# ---------------------------------------------------------------------------
# scheduler API
# ---------------------------------------------------------------------------


@pytest.fixture()
def api():
    runner = ServiceTestRunner(YAML)
    runner.run([
        AdvanceCycles(1),
        SendTaskRunning("web-0-srv"),
        ExpectDeploymentComplete(),
    ])
    server = ApiServer(runner.world.scheduler, auth_token=TOKEN).start()
    yield server
    server.stop()


def test_api_rejects_anonymous_reads_and_verbs(api):
    http("GET", api.url + "/v1/plans", expect=401)
    http("POST", api.url + "/v1/plans/deploy/interrupt", expect=401)
    http("POST", api.url + "/v1/pod/web-0/restart", expect=401)
    # wrong token is as good as none
    http("GET", api.url + "/v1/plans", token="wrong", expect=401)


def test_api_health_stays_open_for_probes(api):
    body = http("GET", api.url + "/v1/health")
    assert body["healthy"]


def test_api_accepts_bearer_token(api):
    plans = http("GET", api.url + "/v1/plans", token=TOKEN)
    assert "deploy" in plans
    http("POST", api.url + "/v1/plans/deploy/restart", token=TOKEN)


# ---------------------------------------------------------------------------
# agent daemon
# ---------------------------------------------------------------------------


def test_agent_daemon_rejects_anonymous_everything(tmp_path):
    daemon = AgentDaemon("h0", str(tmp_path), auth_token=TOKEN).start()
    try:
        base = daemon.url
        # launch IS remote command execution — the critical 401
        http("POST", base + "/v1/agent/launch", body={"tasks": []},
             expect=401)
        http("POST", base + "/v1/agent/kill",
             body={"task_id": "x"}, expect=401)
        http("GET", base + "/v1/agent/info", expect=401)
        http("GET", base + "/v1/agent/sandbox?task=a&file=stdout",
             expect=401)
        # the holder of the cluster token proceeds
        out = http("POST", base + "/v1/agent/launch",
                   body={"tasks": []}, token=TOKEN)
        assert out == {"launched": []}
        info = http("GET", base + "/v1/agent/info", token=TOKEN)
        assert info["host_id"] == "h0"
    finally:
        daemon.stop()


# ---------------------------------------------------------------------------
# state server
# ---------------------------------------------------------------------------


def test_state_server_rejects_anonymous_kv(tmp_path):
    server = StateServer(
        FileWalPersister(str(tmp_path / "wal")), auth_token=TOKEN
    ).start()
    try:
        http("POST", server.url + "/v1/kv/set",
             body={"path": "/x", "value": "aGk="}, expect=401)
        anon = RemotePersister(server.url)
        with pytest.raises(PersisterError):
            anon.set("/x", b"clobber")
        authed = RemotePersister(server.url, auth_token=TOKEN)
        authed.set("/x", b"hi")
        assert authed.get("/x") == b"hi"
    finally:
        server.stop()


def test_leases_survive_state_server_restart(tmp_path):
    """ADVICE r2: leases were in-memory only — a state-server restart
    silently dropped the scheduler instance lock.  Now they persist
    through the backend WAL."""
    wal_dir = str(tmp_path / "wal")
    server = StateServer(FileWalPersister(wal_dir), auth_token=TOKEN).start()
    holder = RemoteLocker(
        server.url, "svc", "holder-1", ttl_s=30.0, auth_token=TOKEN
    )
    assert holder.acquire()
    holder._stop.set()  # stop renewals; the lease itself stays live
    server.stop()

    revived = StateServer(FileWalPersister(wal_dir), auth_token=TOKEN).start()
    try:
        standby = RemoteLocker(
            revived.url, "svc", "standby-2", ttl_s=30.0, auth_token=TOKEN
        )
        assert not standby.acquire(), (
            "standby must NOT steal a live lease across a state-server "
            "restart"
        )
    finally:
        revived.stop()


def test_locker_fires_on_lost_when_lease_stolen(tmp_path):
    """ADVICE r2: a holder that stalls past the TTL must learn it lost
    the lease (CuratorLocker exits the process on ZK lock loss)."""
    server = StateServer(auth_token=TOKEN).start()
    lost = threading.Event()
    reasons = []
    try:
        holder = RemoteLocker(
            server.url, "svc", "holder-1", ttl_s=0.9, auth_token=TOKEN
        )
        holder.on_lost = lambda reason: (reasons.append(reason), lost.set())
        assert holder.acquire()
        # simulate the holder stalling past the TTL: expire its lease
        # server-side and hand it to a standby
        with server._lock:
            server._leases["svc"] = ("standby-2", time.time() + 60)
        assert lost.wait(5.0), "on_lost never fired"
        assert "another scheduler" in reasons[0]
    finally:
        server.stop()


def test_state_server_tls_roundtrip(tmp_path):
    """HTTPS from the in-repo CA: client verifies the server cert."""
    certs = str(tmp_path / "certs")
    certs_main(["--dir", certs, "--hosts", "127.0.0.1"])
    server = StateServer(
        auth_token=TOKEN,
        tls=(os.path.join(certs, "127.0.0.1.cert.pem"),
             os.path.join(certs, "127.0.0.1.key.pem")),
    ).start()
    try:
        assert server.url.startswith("https://")
        client = RemotePersister(
            server.url, auth_token=TOKEN,
            ca_file=os.path.join(certs, "ca.pem"),
        )
        client.set("/tls-check", b"encrypted")
        assert client.get("/tls-check") == b"encrypted"
        # a client that does NOT trust the CA refuses the connection
        untrusting = RemotePersister(server.url, auth_token=TOKEN)
        with pytest.raises(PersisterError):
            untrusting.set("/x", b"y")
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# serve e2e: the full control plane under one cluster token
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_serve_e2e_authenticated_control_plane(tmp_path):
    """Scheduler + agents + state server all require the token;
    anonymous launch/kill/kv-set/plan verbs are rejected while the
    authenticated deploy completes across real processes."""
    token_file = tmp_path / "token"
    token_file.write_text(TOKEN + "\n")
    auth_args = ["--auth-token-file", str(token_file)]
    run_env = {**os.environ, "AUTH_TOKEN": ""}

    import subprocess
    import sys

    state_announce = tmp_path / "state-announce"
    state_log = open(tmp_path / "state.log", "ab")
    state_proc = subprocess.Popen(
        [sys.executable, "-m", "dcos_commons_tpu", "state-server",
         "--data-dir", str(tmp_path / "cluster-state"),
         "--announce-file", str(state_announce), *auth_args],
        cwd=REPO, stdout=state_log, stderr=subprocess.STDOUT, env=run_env,
    )
    agents = []
    scheduler = None
    try:
        state_url = wait_for(
            lambda: state_announce.exists()
            and state_announce.read_text().strip(),
            what="state server announce",
        )
        agents = [
            AgentProcess(f"h{i}", str(tmp_path / f"agent-{i}"), REPO,
                         extra_args=auth_args)
            for i in range(2)
        ]
        topology = tmp_path / "topology.yml"
        topology.write_text("hosts:\n" + "".join(
            f"  - host_id: {a.host_id}\n    agent_url: {a.url}\n"
            "    cpus: 4.0\n    memory_mb: 8192\n"
            for a in agents
        ))
        svc = tmp_path / "svc.yml"
        svc.write_text(
            "name: webfarm\n"
            "pods:\n"
            "  app:\n"
            "    count: 2\n"
            "    placement: 'max-per-host:1'\n"
            "    tasks:\n"
            "      server:\n"
            "        goal: RUNNING\n"
            "        cmd: \"sleep 120\"\n"
            "        cpus: 0.1\n"
            "        memory: 32\n"
        )
        scheduler = SchedulerProcess(
            str(svc), str(topology), str(tmp_path / "scheduler"),
            env={"ENABLE_BACKOFF": "false"},
            repo_root=REPO,
            extra_args=[*auth_args, "--state-url", state_url],
            auth_token=TOKEN,
        )
        client = scheduler.client()
        client.wait_for_completed_deployment(timeout_s=90)

        # every anonymous mutation on every surface: rejected
        http("POST", scheduler.url + "/v1/plans/deploy/restart", expect=401)
        http("POST", agents[0].url + "/v1/agent/launch",
             body={"tasks": [{"info": {
                 "task_id": "evil", "name": "evil", "cmd": "id",
             }}]}, expect=401)
        http("POST", agents[0].url + "/v1/agent/kill",
             body={"task_id": "app-0-server"}, expect=401)
        http("POST", state_url + "/v1/kv/set",
             body={"path": "/pwn", "value": "cHdu"}, expect=401)

        # the authenticated plane still works end to end
        assert set(client.task_ids()) == {"app-0-server", "app-1-server"}
        health = client.get("/v1/health")
        assert health["healthy"]
    finally:
        if scheduler is not None:
            scheduler.terminate()
        for agent in agents:
            agent.stop()
        from dcos_commons_tpu.testing.integration import reap_orphan_tasks

        reap_orphan_tasks(agents)
        state_proc.terminate()
        state_proc.wait(timeout=10)
        state_log.close()


def test_partial_tls_config_is_a_hard_error():
    """Half a cert/key pair must refuse to start, not silently serve
    plaintext (code-review r3)."""
    from dcos_commons_tpu.scheduler.config import SchedulerConfig
    from dcos_commons_tpu.security.auth import tls_pair

    with pytest.raises(ValueError):
        tls_pair("cert.pem", "")
    with pytest.raises(ValueError):
        tls_pair("", "key.pem")
    assert tls_pair("", "") is None
    assert tls_pair("c", "k") == ("c", "k")
    with pytest.raises(ValueError):
        SchedulerConfig(tls_cert_file="cert.pem").api_tls


def test_tls_handshake_stall_does_not_freeze_server(tmp_path):
    """A client that opens TCP and never speaks TLS must not block the
    accept loop (code-review r3): other clients keep being served."""
    import socket

    certs = str(tmp_path / "certs")
    certs_main(["--dir", certs, "--hosts", "127.0.0.1"])
    server = StateServer(
        auth_token=TOKEN,
        tls=(os.path.join(certs, "127.0.0.1.cert.pem"),
             os.path.join(certs, "127.0.0.1.key.pem")),
    ).start()
    stalled = socket.create_connection(
        ("127.0.0.1", int(server.url.rsplit(":", 1)[1])), timeout=5
    )
    try:
        time.sleep(0.2)  # let the server accept the silent connection
        client = RemotePersister(
            server.url, auth_token=TOKEN,
            ca_file=os.path.join(certs, "ca.pem"), timeout_s=5.0,
        )
        client.set("/alive", b"yes")
        assert client.get("/alive") == b"yes"
    finally:
        stalled.close()
        server.stop()
