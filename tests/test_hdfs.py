"""frameworks/hdfs: the stateful multi-pod-type service.

Reference: frameworks/hdfs (3 pod types, ordered deploy,
HdfsRecoveryPlanOverrider name-node choreography) and BASELINE
config #5 (hdfs + jax co-scheduled on shared inventory).
"""

import os

import pytest
import sys

from dcos_commons_tpu.common import TaskState, TaskStatus
from dcos_commons_tpu.multi import MultiServiceScheduler
from dcos_commons_tpu.offer.inventory import SliceInventory, make_test_fleet
from dcos_commons_tpu.plan.status import Status
from dcos_commons_tpu.recovery.monitor import TestingFailureMonitor
from dcos_commons_tpu.scheduler import SchedulerConfig
from dcos_commons_tpu.specification.yaml_spec import from_yaml
from dcos_commons_tpu.storage import MemPersister
from dcos_commons_tpu.testing import (
    AdvanceCycles,
    ExpectDeploymentComplete,
    ExpectLaunchedTasks,
    ExpectPlanStatus,
    FakeAgent,
    SendTaskFailed,
    SendTaskFinished,
    SendTaskRunning,
    ServiceTestRunner,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HDFS_DIR = os.path.join(REPO, "frameworks", "hdfs")
sys.path.insert(0, HDFS_DIR)

from scheduler import make_name_node_overrider  # noqa: E402


def load_svc() -> str:
    with open(os.path.join(HDFS_DIR, "svc.yml")) as f:
        return f.read()


def deploy_ticks():
    """Scripted full deploy: journal x3 (parallel) -> name (format,
    node; bootstrap, node) -> data x3 (parallel)."""
    return [
        AdvanceCycles(1),
        ExpectLaunchedTasks(
            "journal-0-node", "journal-1-node", "journal-2-node"
        ),
        SendTaskRunning("journal-0-node"),
        SendTaskRunning("journal-1-node"),
        SendTaskRunning("journal-2-node"),
        AdvanceCycles(1),
        ExpectLaunchedTasks("name-0-format"),
        SendTaskFinished("name-0-format"),
        AdvanceCycles(1),
        ExpectLaunchedTasks("name-0-node"),
        SendTaskRunning("name-0-node"),
        AdvanceCycles(1),
        ExpectLaunchedTasks("name-1-bootstrap"),
        SendTaskFinished("name-1-bootstrap"),
        AdvanceCycles(1),
        ExpectLaunchedTasks("name-1-node"),
        SendTaskRunning("name-1-node"),
        AdvanceCycles(1),
        ExpectLaunchedTasks("data-0-node", "data-1-node", "data-2-node"),
        SendTaskRunning("data-0-node"),
        SendTaskRunning("data-1-node"),
        SendTaskRunning("data-2-node"),
        ExpectDeploymentComplete(),
    ]


def make_runner(**kw):
    hosts = make_test_fleet(host_grid=(3, 3), chip_block=(1, 1))
    return ServiceTestRunner(load_svc(), hosts=hosts, **kw)


def test_ordered_multi_pod_deploy():
    """Deploy honors the phase order and per-instance step
    choreography of the custom plan (journal -> name -> data)."""
    runner = make_runner()
    runner.run(deploy_ticks())
    # format ran only on name-0, bootstrap only on name-1
    assert runner.world.agent.task_id_of("name-1-format") is None
    assert runner.world.agent.task_id_of("name-0-bootstrap") is None


def test_name_node_replace_runs_bootstrap_choreography():
    """PERMANENT name-node failure triggers the overrider phase:
    bootstrap re-runs BEFORE the node relaunches (reference:
    HdfsRecoveryPlanOverrider; hook recovery/manager.py)."""
    runner = make_runner()
    # wire the overrider + a monitor that makes every failure PERMANENT
    spec = runner.spec

    def hook(builder):
        builder.add_recovery_overrider(make_name_node_overrider(spec))
        builder.set_failure_monitor(
            TestingFailureMonitor(permanent_tasks=["name-1-node"])
        )

    runner._builder_hook = hook
    runner.run(deploy_ticks())
    runner.run([
        SendTaskFailed("name-1-node"),
        AdvanceCycles(1),
    ])
    recovery = runner.world.scheduler.plan("recovery")
    phase = recovery.phases[0]
    assert [s.name for s in phase.steps] == [
        "bootstrap-name-1", "relaunch-name-1"
    ]
    runner.run([
        ExpectLaunchedTasks("name-1-bootstrap"),
        SendTaskFinished("name-1-bootstrap"),
        AdvanceCycles(1),
        ExpectLaunchedTasks("name-1-node"),
        SendTaskRunning("name-1-node"),
        ExpectPlanStatus("recovery", Status.COMPLETE),
    ])
    # bootstrap ran twice total: once at deploy, once for the replace
    assert len(runner.world.agent.launches_of("name-1-bootstrap")) == 2


def test_journal_failure_uses_default_recovery():
    """The overrider only fires for name-pod PERMANENT replaces;
    journal failures keep the default single-step recovery."""
    runner = make_runner()
    spec = runner.spec
    runner._builder_hook = lambda b: b.add_recovery_overrider(
        make_name_node_overrider(spec)
    )
    runner.run(deploy_ticks())
    runner.run([
        SendTaskFailed("journal-2-node"),
        AdvanceCycles(1),
        ExpectLaunchedTasks("journal-2-node"),
        SendTaskRunning("journal-2-node"),
        ExpectPlanStatus("recovery", Status.COMPLETE),
    ])
    assert len(runner.world.agent.launches_of("journal-2-node")) == 2


def test_hdfs_jax_coschedule_shared_inventory():
    """BASELINE config #5: hdfs + the jax gang pod co-scheduled by the
    multi scheduler on one fleet without resource conflicts."""
    with open(os.path.join(REPO, "frameworks", "jax", "svc.yml")) as f:
        jax_yaml = f.read()
    fleet = make_test_fleet(host_grid=(3, 3), chip_block=(2, 2))
    agent = FakeAgent()
    multi = MultiServiceScheduler(
        persister=MemPersister(),
        inventory=SliceInventory(fleet),
        agent=agent,
        scheduler_config=SchedulerConfig(backoff_enabled=False),
    )
    multi.add_service(from_yaml(load_svc()))
    multi.add_service(from_yaml(jax_yaml, env={"TPU_TOPOLOGY": "4x4"}))
    for _ in range(16):
        multi.run_cycle()
        for info in agent.launched:
            goal = "FINISHED" if info.name.split("-")[-1] in (
                "format", "bootstrap"
            ) else "RUNNING"
            agent.send(TaskStatus(
                task_id=info.task_id,
                state=TaskState.FINISHED if goal == "FINISHED"
                else TaskState.RUNNING,
                ready=True,
            ))
        hdfs = multi.get_service("hdfs")
        trainer = multi.get_service("jax-trainer")
        if (
            hdfs.deploy_manager.get_plan().is_complete
            and trainer.deploy_manager.get_plan().is_complete
        ):
            break
    hdfs = multi.get_service("hdfs")
    trainer = multi.get_service("jax-trainer")
    assert hdfs.deploy_manager.get_plan().is_complete
    assert trainer.deploy_manager.get_plan().is_complete
    # gang workers each got a whole host's chips; no chip is
    # double-booked across the two services' namespaced ledgers
    reservations = [
        r
        for svc in (hdfs, trainer)
        for r in svc.ledger.all()
    ]
    by_host_chips = {}
    for r in reservations:
        for c in r.chip_ids:
            key = (r.host_id, c)
            assert key not in by_host_chips, f"chip double-booked: {key}"
            by_host_chips[key] = r.task_name
    # hdfs placed all 8 tasks, jax placed 4 gang workers
    assert len(agent.launched) >= 12


def test_name_volume_shared_between_sibling_tasks(tmp_path):
    """Real-agent proof of the shared per-instance volume: format
    writes name-data/fsimage, and the node task (whose command FAILS
    unless the file exists) reads it from the SAME durable directory.
    """
    import time

    from dcos_commons_tpu.agent.local import LocalProcessAgent
    from dcos_commons_tpu.offer.inventory import SliceInventory, TpuHost
    from dcos_commons_tpu.scheduler import SchedulerBuilder, SchedulerConfig
    from dcos_commons_tpu.storage import MemPersister

    spec = from_yaml(load_svc(), env={
        "SLEEP_DURATION": "600",
        "JOURNAL_COUNT": "1",
        "DATA_COUNT": "1",
    })
    builder = SchedulerBuilder(
        spec,
        SchedulerConfig(
            sandbox_root=str(tmp_path / "sbx"),
            backoff_enabled=False,
            revive_capacity=1_000_000,
        ),
        MemPersister(),
    )
    hosts = [TpuHost(host_id=f"h{i}", cpus=8.0, memory_mb=8192)
             for i in range(3)]
    builder.set_inventory(SliceInventory(hosts))
    agent = LocalProcessAgent(str(tmp_path / "sbx"))
    builder.set_agent(agent)
    scheduler = builder.build()
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        scheduler.run_cycle()
        if scheduler.deploy_manager.get_plan().is_complete:
            break
        time.sleep(0.05)
    assert scheduler.deploy_manager.get_plan().is_complete
    # both sibling sandboxes resolve name-data to ONE durable dir
    fmt = os.path.realpath(str(tmp_path / "sbx/name-0-format/name-data"))
    node = os.path.realpath(str(tmp_path / "sbx/name-0-node/name-data"))
    assert fmt == node
    assert (tmp_path / "sbx/name-0-node/name-data/fsimage").exists()
    agent.shutdown()


@pytest.mark.slow
def test_custom_namenodes_endpoint_served(tmp_path):
    """Framework-specific HTTP resources (reference: SeedsResource)
    register through the runner's routes hook and serve next to the
    SDK routes — driven as a real served process."""
    import json
    import subprocess
    import time
    import urllib.request

    topo = tmp_path / "topology.yml"
    topo.write_text("hosts:\n" + "".join(
        f"  - host_id: h{i}\n    cpus: 8\n    memory_mb: 8192\n"
        for i in range(3)
    ))
    proc = subprocess.Popen(
        [sys.executable, "frameworks/hdfs/scheduler.py",
         "frameworks/hdfs/svc.yml",
         "--topology", str(topo), "--port", "0",
         "--state-dir", str(tmp_path / "state"),
         "--sandbox-root", str(tmp_path / "sbx"),
         "--announce-file", str(tmp_path / "announce"),
         "--env", "SLEEP_DURATION=600"],
        cwd=REPO,
    )
    try:
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and not (
            tmp_path / "announce"
        ).exists():
            time.sleep(0.1)
        url = (tmp_path / "announce").read_text().strip()

        def get(p):
            with urllib.request.urlopen(url + p, timeout=5) as r:
                return json.loads(r.read())

        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            body = get("/v1/namenodes")
            nodes = {n["name"]: n for n in body["namenodes"]}
            if all(
                n["state"] == "TASK_RUNNING" and n["host"]
                for n in nodes.values()
            ):
                break
            time.sleep(0.5)
        assert set(nodes) == {"name-0-node", "name-1-node"}
        assert all(n["host"] for n in nodes.values())
        # SDK routes still serve beside the custom one
        assert get("/v1/health")["healthy"]
    finally:
        proc.terminate()
        proc.wait(timeout=20)


@pytest.mark.slow
def test_backup_restore_sidecar_plans_via_cli(tmp_path):
    """Parameterized sidecar plans end to end, all via CLI verbs:
    `plan start backup -p BACKUP_DIR=...` snapshots every data
    volume's payload, the payload is destroyed on disk, and
    `plan start restore -p BACKUP_DIR=...` brings it back intact.

    Reference: cassandra's backup/restore sidecar plans driven by
    PlansQueries start-with-env (PlansQueries.java:47-231,
    frameworks/cassandra/src/main/dist/svc.yml)."""
    import glob
    import json
    import subprocess
    import time

    topo = tmp_path / "topology.yml"
    topo.write_text("hosts:\n" + "".join(
        f"  - host_id: h{i}\n    cpus: 8\n    memory_mb: 8192\n"
        for i in range(3)
    ))
    proc = subprocess.Popen(
        [sys.executable, "frameworks/hdfs/scheduler.py",
         "frameworks/hdfs/svc.yml",
         "--topology", str(topo), "--port", "0",
         "--state-dir", str(tmp_path / "state"),
         "--sandbox-root", str(tmp_path / "sbx"),
         "--announce-file", str(tmp_path / "announce"),
         "--env", "SLEEP_DURATION=600",
         "--env", "JOURNAL_COUNT=1",
         "--env", "DATA_COUNT=2"],
        cwd=REPO,
    )
    try:
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and not (
            tmp_path / "announce"
        ).exists():
            time.sleep(0.1)
        url = (tmp_path / "announce").read_text().strip()

        def cli(*argv):
            out = subprocess.run(
                [sys.executable, "-m", "dcos_commons_tpu", "cli",
                 "--url", url, *argv],
                cwd=REPO, capture_output=True, text=True, timeout=30,
            )
            assert out.returncode == 0, out.stderr
            return json.loads(out.stdout)

        def wait_plan(plan, timeout_s=120):
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                if cli("plan", "status", plan)["status"] == "COMPLETE":
                    return
                time.sleep(0.5)
            raise AssertionError(
                f"plan {plan} not COMPLETE: {cli('plan', 'status', plan)}"
            )

        wait_plan("deploy")

        # stamp each data volume with a unique payload, then back up
        data_logs = sorted(glob.glob(
            str(tmp_path / "sbx" / "data-*-node" / "data-data" / "data.log")
        ))
        assert len(data_logs) == 2, data_logs
        for i, path in enumerate(data_logs):
            with open(path, "a") as f:
                f.write(f"precious-payload-{i}\n")
        originals = {p: open(p).read() for p in data_logs}

        backup_dir = tmp_path / "backups" / "snap-1"
        cli("plan", "start", "backup",
            "-p", f"BACKUP_DIR={backup_dir}")
        wait_plan("backup")
        assert len(glob.glob(str(backup_dir / "data-*" / "data.log"))) == 2

        # catastrophe: the payload vanishes from every data volume
        for path in data_logs:
            os.remove(path)

        cli("plan", "start", "restore",
            "-p", f"BACKUP_DIR={backup_dir}")
        wait_plan("restore")
        for path, content in originals.items():
            assert open(path).read() == content, f"payload lost: {path}"
    finally:
        proc.terminate()
        proc.wait(timeout=20)
