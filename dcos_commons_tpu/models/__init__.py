"""Workload model zoo for frameworks/jax.

The reference SDK has no data plane (SURVEY.md: "the workloads are
whatever the service YAML launches"); these are the flagship workloads
the TPU rebuild ships so a user can stand up real training pods:

- transformer.py  decoder-only LM, pure-JAX pytrees, scan-over-layers,
                  bf16 compute, RoPE + GQA + SwiGLU, pallas kernels,
                  dp/fsdp/tp/sp shardings for pjit
- mlp.py          MNIST-scale MLP (the BASELINE.json config-3 demo)
"""

from dcos_commons_tpu.models.transformer import (
    TransformerConfig,
    init_params,
    loss_fn,
    make_train_step,
    forward,
)
from dcos_commons_tpu.models.mlp import MlpConfig, mlp_forward, mlp_init, mlp_train_step

__all__ = [
    "MlpConfig",
    "TransformerConfig",
    "forward",
    "init_params",
    "loss_fn",
    "make_train_step",
    "mlp_forward",
    "mlp_init",
    "mlp_train_step",
]
