"""LocalProcessAgent: run tasks as real subprocesses with sandboxes.

Plays the role of the Mesos agent + sdk/bootstrap for a simulated
fleet: each task gets a sandbox directory, its env contract (the
PodInfoBuilder-assembled env), readiness-check execution (reference:
readiness spec stored as a label, PodInfoBuilder.java:511-526, executed
task-side), and health-check supervision with kill-on-max-failures.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from dcos_commons_tpu.common import TaskInfo, TaskState, TaskStatus
from dcos_commons_tpu.specification.specs import (
    HealthCheckSpec,
    ReadinessCheckSpec,
)


def prepare_templates(
    task_env: Dict[str, str],
    templates: Optional[List[dict]],
    auth_token: str = "",
    ca_file: str = "",
) -> List[Tuple[str, str]]:
    """Fetch + render config templates; no filesystem writes.

    The task-side half of the per-task config plane: the reference's
    bootstrap binary fetches each template from the scheduler's
    /v1/artifacts endpoint and mustache-renders it against the task env
    (sdk/bootstrap/main.go:291-376).  Each template dict carries
    ``dest`` (sandbox-relative path) and either inline ``content`` or
    a ``url`` to fetch from the scheduler.  Kept free of locks and
    sandbox state: URL fetches can be slow and must not stall the
    agent's kill/poll handling.
    """
    out: List[Tuple[str, str]] = []
    for template in templates or []:
        if "error" in template:
            raise ValueError(template["error"])
        dest = template["dest"]
        content = template.get("content")
        if content is None:
            url = template.get("url")
            if not url:
                raise ValueError(
                    f"template {template.get('name')!r} has neither "
                    "content nor url"
                )
            import urllib.request

            from dcos_commons_tpu.security import auth as _auth

            # the scheduler's /v1/artifacts is bearer-protected like
            # every other route; the daemon holds the cluster token
            req = urllib.request.Request(
                url, headers=_auth.auth_headers(auth_token)
            )
            ctx = (
                _auth.client_ssl_context(ca_file)
                if url.startswith("https") else None
            )
            with urllib.request.urlopen(req, timeout=10, context=ctx) as resp:
                content = resp.read().decode("utf-8")
        from dcos_commons_tpu.specification.yaml_spec import render_template

        out.append((dest, render_template(content, task_env)))
    return out


def _sandbox_path(sandbox: str, dest: str, what: str) -> str:
    """Resolve a sandbox-relative dest, rejecting escapes (dest is
    remote-controlled via the launch request)."""
    root = os.path.normpath(sandbox)
    if os.path.isabs(dest):
        raise ValueError(f"{what} dest must be sandbox-relative: {dest}")
    path = os.path.normpath(os.path.join(root, dest))
    if not path.startswith(root + os.sep):
        raise ValueError(f"{what} dest escapes the sandbox: {dest}")
    return path


def stage_uris(
    uris: Optional[List[dict]],
    cache_dir: str,
    ca_file: str = "",
) -> List[Tuple[dict, str]]:
    """Download task artifacts; no sandbox writes (slow network work
    happens OUTSIDE the agent lock, like prepare_templates).

    The task-side half of the reference's pre-launch artifact fetch
    (``uris:`` in YAML, fetched by the Mesos fetcher before the task
    command runs; YAMLToInternalMappers.java:397).  Digest-pinned
    artifacts (``sha256``) are cached per host under ``cache_dir``
    keyed by digest — a TPU fleet stages the same corpus/tokenizer on
    every host, and relaunches must not re-download gigabytes.
    Unpinned artifacts are fetched fresh every launch (a mutable URL
    must not serve a stale cache).  The cluster bearer token is NEVER
    attached: these are arbitrary operator URLs, not scheduler routes
    — leaking the token to an external host would hand out the
    control plane.  Returns [(entry, staged_file_path)].
    """
    import hashlib
    import tempfile
    import urllib.request

    def sha256_file(path: str) -> str:
        digest = hashlib.sha256()
        with open(path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                digest.update(chunk)
        return digest.hexdigest()

    staged: List[Tuple[dict, str]] = []
    os.makedirs(cache_dir, exist_ok=True)
    try:
        for entry in uris or []:
            uri = entry.get("uri", "")
            if not uri:
                raise ValueError(f"artifact entry without a uri: {entry!r}")
            pin = str(entry.get("sha256", "")).lower()
            if pin:
                cached = os.path.join(cache_dir, pin)
                if os.path.exists(cached) and sha256_file(cached) == pin:
                    staged.append((entry, cached))
                    continue
                if os.path.exists(cached):
                    os.remove(cached)  # corrupted cache entry: refetch
            ctx = None
            if uri.startswith("https"):
                from dcos_commons_tpu.security import auth as _auth

                ctx = _auth.client_ssl_context(ca_file)
            # STREAM to disk while hashing: artifacts are corpus-sized
            # (gigabytes) — buffering one in RAM would OOM the agent
            # and every task it supervises
            digest = hashlib.sha256()
            fd, tmp = tempfile.mkstemp(dir=cache_dir, prefix=".fetch-")
            try:
                with os.fdopen(fd, "wb") as f, urllib.request.urlopen(
                    uri, timeout=120, context=ctx
                ) as resp:
                    for chunk in iter(lambda: resp.read(1 << 20), b""):
                        digest.update(chunk)
                        f.write(chunk)
                if pin and digest.hexdigest() != pin:
                    raise ValueError(
                        f"artifact {uri} digest mismatch: expected "
                        f"{pin}, got {digest.hexdigest()}"
                    )
            except BaseException:
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                raise
            if pin:
                os.replace(tmp, os.path.join(cache_dir, pin))
                staged.append((entry, os.path.join(cache_dir, pin)))
            else:
                staged.append((entry, tmp))
    except BaseException:
        discard_staged(staged)
        raise
    return staged


def discard_staged(staged: List[Tuple[dict, str]]) -> None:
    """Remove unpinned temp files that were never consumed by
    install_uris (launch aborted between stage and install) — churny
    relaunches must not fill the agent's disk with orphans.  Pinned
    entries live in the cache by design and are kept."""
    for entry, path in staged:
        if entry.get("sha256"):
            continue
        try:
            os.remove(path)
        except OSError:
            pass


def install_uris(
    sandbox: str, staged: List[Tuple[dict, str]]
) -> None:
    """Place staged artifacts into the sandbox: copy to dest
    (traversal-safe), optional +x, optional tar extraction (member
    paths validated — a hostile archive must not escape).  Unpinned
    temp files are consumed."""
    import shutil
    import tarfile

    for entry, source in staged:
        dest = entry.get("dest") or \
            entry["uri"].rstrip("/").rsplit("/", 1)[-1].split("?")[0]
        path = _sandbox_path(sandbox, dest, "artifact")
        os.makedirs(os.path.dirname(path) or sandbox, exist_ok=True)
        pinned = bool(entry.get("sha256"))
        if pinned:
            shutil.copyfile(source, path)  # cache entry stays
        else:
            os.replace(source, path)
        if entry.get("executable"):
            os.chmod(path, os.stat(path).st_mode | 0o755)
        if entry.get("extract"):
            target_dir = os.path.dirname(path) or sandbox
            with tarfile.open(path) as tar:
                for member in tar.getmembers():
                    member_path = os.path.normpath(
                        os.path.join(target_dir, member.name)
                    )
                    root = os.path.normpath(sandbox)
                    # './' members (tar -C dir .) normalize to the
                    # root itself — benign, allowed
                    if member_path != root and \
                            not member_path.startswith(root + os.sep):
                        raise ValueError(
                            f"archive member escapes the sandbox: "
                            f"{member.name}"
                        )
                    if member.issym() or member.islnk():
                        raise ValueError(
                            f"archive member is a link: {member.name}"
                        )
                try:
                    tar.extractall(target_dir, filter="data")
                except TypeError:  # pre-3.12: manual checks above apply
                    tar.extractall(target_dir)


def write_templates(sandbox: str, rendered: List[Tuple[str, str]]) -> None:
    """Write rendered templates, confined to the sandbox: ``dest`` is
    remote-controlled (launch request), so absolute paths and ``..``
    escapes are rejected."""
    root = os.path.normpath(sandbox)
    for dest, text in rendered:
        if os.path.isabs(dest):
            raise ValueError(f"template dest must be sandbox-relative: {dest}")
        path = os.path.normpath(os.path.join(root, dest))
        if not path.startswith(root + os.sep):
            raise ValueError(f"template dest escapes the sandbox: {dest}")
        os.makedirs(os.path.dirname(path) or root, exist_ok=True)
        with open(path, "w") as f:
            f.write(text)


@dataclass
class _Running:
    info: TaskInfo
    # Popen when this agent process launched the task; None for a task
    # recovered from a previous agent incarnation (tracked by pid +
    # the supervisor's durable exit_status record)
    process: Optional[subprocess.Popen]
    sandbox: str
    readiness: Optional[ReadinessCheckSpec]
    health: Optional[HealthCheckSpec]
    started_at: float
    pid: int = 0
    pid_identity: str = ""          # /proc start time: pid-reuse guard
    native: bool = False            # supervised by the C++ task_exec
    record_dir: str = ""            # per-INCARNATION lifecycle records
    ready_reported: bool = False
    running_reported: bool = False
    health_failures: int = 0
    last_check_at: float = 0.0
    last_health_at: float = 0.0
    kill_requested: bool = False
    kill_deadline: float = 0.0

    def exit_code(self) -> Optional[int]:
        """None while alive; the exit code once done; -1 when the fate
        is unknowable (supervisor lost / non-native recovery).

        Self-launched tasks short-circuit on the Popen (the native
        supervisor exits WITH the child's code); recovered tasks read
        the supervisor's durable exit_status record."""
        if self.process is not None:
            return self.process.poll()
        status_path = os.path.join(
            self.record_dir or self.sandbox, "exit_status"
        )
        try:
            with open(status_path) as f:
                return int(f.read().strip())
        except (OSError, ValueError):
            pass
        if self.pid and not _pid_alive(self.pid, self.pid_identity):
            # pid gone (or recycled by another process) without a
            # durable record: the fate is unknowable
            return -1
        return None


def _rlimit_preexec(rlimits: List[dict]):
    """Child-side hook applying per-task resource limits between fork
    and exec (reference: RLimitSpec -> Mesos RLimitInfo, enforced by
    the containerizer; here setrlimit(2) directly).  A limit that
    cannot be applied fails the launch — silently running without the
    isolation the spec demanded is worse than not running."""
    import resource

    pairs = []
    for rl in rlimits:
        res = getattr(resource, str(rl["name"]))
        soft = int(rl.get("soft", -1))
        hard = int(rl.get("hard", -1))
        pairs.append((
            res,
            resource.RLIM_INFINITY if soft < 0 else soft,
            resource.RLIM_INFINITY if hard < 0 else hard,
        ))

    def apply():
        for res, soft, hard in pairs:
            resource.setrlimit(res, (soft, hard))

    return apply


def _proc_identity(pid: int) -> str:
    """Process start time from /proc — distinguishes a live pid from a
    recycled one.  Empty string when unavailable (non-Linux)."""
    try:
        with open(f"/proc/{pid}/stat") as f:
            fields = f.read().rsplit(") ", 1)[-1].split()
        # field 22 of /proc/pid/stat overall = index 19 after comm
        return fields[19]
    except (OSError, IndexError):
        return ""


def _pid_alive(pid: int, identity: str = "") -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        pass
    if identity:
        current = _proc_identity(pid)
        if current and current != identity:
            return False  # pid recycled by an unrelated process
    return True


class LocalProcessAgent:
    """One agent process simulating every host in the fleet.

    ``readiness_for``/``health_for`` map task *spec* checks in; the
    scheduler passes them at launch via TaskInfo labels is avoided —
    instead the scheduler registers specs with the agent directly
    (launch_with_checks), keeping TaskInfo JSON-small.
    """

    def __init__(self, workdir: str, use_native: bool = True,
                 auth_token: str = "", ca_file: str = ""):
        # anchor the sandbox root: the $SANDBOX env contract and the
        # durable supervisor records are consumed from the TASK's cwd
        # (the sandbox itself), so a relative --sandbox-root would
        # hand every task a path that resolves nowhere
        self._workdir = os.path.abspath(workdir)
        # credentials for pulling templates off the scheduler's
        # bearer-protected /v1/artifacts endpoint
        self._auth_token = auth_token
        self._ca_file = ca_file
        self._tasks: Dict[str, _Running] = {}
        self._pending: List[TaskStatus] = []
        # recovered terminal fates whose records retire at delivery
        self._undelivered_records: Dict[str, str] = {}
        self._lock = threading.RLock()
        self._use_native = use_native
        if use_native:
            # build the supervisor binary NOW, before any lock is ever
            # held: a first-launch g++ run under the agent lock would
            # freeze poll()/status delivery for every running task
            from dcos_commons_tpu.native import task_exec_path

            task_exec_path()
        os.makedirs(workdir, exist_ok=True)
        with self._lock:
            self._recover_tasks_locked()

    def _recover_tasks_locked(self) -> None:
        """Rebuild task state from sandbox records after an agent
        restart: the C++ supervisor persisted task.json at launch and
        exit_status at exit, so a daemon crash loses no task fates.

        Still-running tasks resume monitoring by pid; exited ones get
        their terminal status synthesized exactly once (the record is
        renamed after delivery)."""
        try:
            names = os.listdir(self._workdir)
        except OSError:
            return
        for name in names:
            sandbox = os.path.join(self._workdir, name)
            super_root = os.path.join(sandbox, ".super")
            try:
                incarnations = os.listdir(super_root)
            except OSError:
                continue
            for task_id in incarnations:
                record_dir = os.path.join(super_root, task_id)
                record_path = os.path.join(record_dir, "task.json")
                if not os.path.isfile(record_path):
                    continue
                try:
                    with open(record_path) as f:
                        record = json.load(f)
                except (OSError, ValueError):
                    continue
                info = TaskInfo.from_dict(record["info"])
                readiness = record.get("readiness")
                health = record.get("health")
                running = _Running(
                    info=info,
                    process=None,
                    sandbox=sandbox,
                    readiness=(
                        ReadinessCheckSpec(**readiness) if readiness else None
                    ),
                    health=HealthCheckSpec(**health) if health else None,
                    started_at=time.monotonic(),
                    pid=int(record.get("pid", 0)),
                    pid_identity=str(record.get("pid_identity", "")),
                    native=bool(record.get("native", False)),
                    record_dir=record_dir,
                )
                code = running.exit_code()
                if code is None:
                    # alive across the restart: resume supervision;
                    # RUNNING is re-reported (status intake idempotent)
                    self._tasks[info.task_id] = running
                    continue
                if code == -1:
                    # no durable record (non-native fallback, or the
                    # supervisor was SIGKILLed): the fate is unknowable
                    # — LOST lets recovery decide, never claiming a
                    # success or failure we cannot prove
                    state = TaskState.LOST
                else:
                    # signal deaths are FAILED: whether the pre-crash
                    # agent had requested the kill is unknowable, and
                    # KILLED (a non-failure state) would wedge a deploy
                    # step waiting on this task
                    state = (
                        TaskState.FINISHED if code == 0
                        else TaskState.FAILED
                    )
                self._pending.append(TaskStatus(
                    task_id=info.task_id,
                    state=state,
                    message=f"recovered after agent restart: exit {code}",
                    agent_id=info.agent_id,
                ))
                # the record is retired only when the fate is HANDED
                # OUT (poll), so a crash before delivery re-recovers it
                self._undelivered_records[info.task_id] = record_path

    # -- Agent --------------------------------------------------------

    def launch(self, task_infos: List[TaskInfo]) -> None:
        for info in task_infos:
            self.launch_one(info)

    def _write_secure_files(
        self, sandbox: str, files: Optional[List[dict]]
    ) -> None:
        """Write launch-shipped secret/TLS files, sandbox-confined,
        with the scheduler-specified mode (0600 for keys).  An entry
        carrying ``error`` fails the launch before the command runs
        (the bootstrap fail-before-cmd discipline)."""
        import base64 as _b64

        for entry in files or []:
            if "error" in entry:
                raise ValueError(entry["error"])
            dest = entry["dest"]
            real_sandbox = os.path.realpath(sandbox)
            path = os.path.realpath(os.path.join(real_sandbox, dest))
            if path != real_sandbox and not path.startswith(
                real_sandbox + os.sep
            ):
                raise ValueError(f"file dest escapes sandbox: {dest!r}")
            os.makedirs(os.path.dirname(path), exist_ok=True)
            content = _b64.b64decode(entry.get("content") or "")
            fd = os.open(
                path,
                os.O_WRONLY | os.O_CREAT | os.O_TRUNC,
                int(entry.get("mode", 0o600)),
            )
            try:
                os.write(fd, content)
            finally:
                os.close(fd)
            # O_CREAT mode is masked by umask and ignored on existing
            # files: enforce explicitly
            os.chmod(path, int(entry.get("mode", 0o600)))

    def _attach_volumes(self, sandbox: str, info: TaskInfo) -> None:
        """Materialize persistent volumes: a durable directory per
        volume key under <workdir>/volumes/, symlinked into the sandbox
        at the declared container path.

        Reference: VolumeEvaluationStage + the Mesos agent's persistent
        volume mount (offer/evaluate/VolumeEvaluationStage.java, 265
        LoC).  TRANSIENT relaunches carry the same volume key and so
        reattach their data; a PERMANENT replace minted a fresh
        reservation (fresh key) and starts empty.
        """
        for container_path, volume_key in sorted(info.volumes.items()):
            durable = os.path.join(
                self._workdir, "volumes", volume_key.replace(os.sep, "_")
            )
            os.makedirs(durable, exist_ok=True)
            link = os.path.join(sandbox, container_path)
            if os.path.islink(link):
                if os.readlink(link) == durable:
                    continue  # relaunch with the same volume key
                # new key into an old sandbox (PERMANENT replace on the
                # same host): relink, or the task would silently
                # reattach the previous incarnation's data
                os.remove(link)
            elif os.path.exists(link):
                continue  # pre-existing real dir: leave it alone
            os.makedirs(os.path.dirname(link), exist_ok=True)
            os.symlink(durable, link)

    def launch_one(
        self,
        info: TaskInfo,
        readiness: Optional[ReadinessCheckSpec] = None,
        health: Optional[HealthCheckSpec] = None,
        templates: Optional[List[dict]] = None,
        files: Optional[List[dict]] = None,
        secret_env: Optional[Dict[str, str]] = None,
        kill_grace_s: float = 5.0,
        uris: Optional[List[dict]] = None,
        rlimits: Optional[List[dict]] = None,
    ) -> None:
        with self._lock:
            if info.task_id in self._tasks:
                return  # idempotent
        # template fetch/render happens OUTSIDE the lock: a slow
        # scheduler artifact endpoint must not block kill/poll/tasks
        # (and thereby trip the fleet's host-down detection)
        try:
            rendered = prepare_templates(
                info.env, templates,
                auth_token=self._auth_token, ca_file=self._ca_file,
            )
        except Exception as e:
            # the reference's bootstrap exits nonzero on a failed
            # template render, failing the task before its command
            # ever runs (sdk/bootstrap/main.go:291-376)
            with self._lock:
                self._pending.append(
                    TaskStatus(
                        task_id=info.task_id,
                        state=TaskState.ERROR,
                        message=f"config template render failed: {e}",
                        agent_id=info.agent_id,
                    )
                )
            return
        # artifact downloads too — network work stays off the lock
        try:
            staged_uris = stage_uris(
                uris,
                cache_dir=os.path.join(self._workdir, ".uri-cache"),
                ca_file=self._ca_file,
            )
        except Exception as e:
            with self._lock:
                self._pending.append(
                    TaskStatus(
                        task_id=info.task_id,
                        state=TaskState.ERROR,
                        message=f"artifact fetch failed: {e}",
                        agent_id=info.agent_id,
                    )
                )
            return
        try:
            with self._lock:
                if info.task_id in self._tasks:
                    return  # raced with a duplicate launch
                sandbox = os.path.join(self._workdir, info.name)
                os.makedirs(sandbox, exist_ok=True)
                try:
                    self._attach_volumes(sandbox, info)
                except OSError as e:
                    self._pending.append(
                        TaskStatus(
                            task_id=info.task_id,
                            state=TaskState.ERROR,
                            message=f"volume provisioning failed: {e}",
                            agent_id=info.agent_id,
                        )
                    )
                    return
                env = dict(os.environ)
                env.update(info.env)
                # secret env values ride the launch request only — merged
                # here at exec time, never part of the persisted TaskInfo
                env.update(secret_env or {})
                env["SANDBOX"] = sandbox
                try:
                    self._write_secure_files(sandbox, files)
                except Exception as e:
                    self._pending.append(
                        TaskStatus(
                            task_id=info.task_id,
                            state=TaskState.ERROR,
                            message=f"secure file provisioning failed: {e}",
                            agent_id=info.agent_id,
                        )
                    )
                    return
                try:
                    write_templates(sandbox, rendered)
                except Exception as e:
                    self._pending.append(
                        TaskStatus(
                            task_id=info.task_id,
                            state=TaskState.ERROR,
                            message=f"config template render failed: {e}",
                            agent_id=info.agent_id,
                        )
                    )
                    return
                try:
                    install_uris(sandbox, staged_uris)
                except Exception as e:
                    self._pending.append(
                        TaskStatus(
                            task_id=info.task_id,
                            state=TaskState.ERROR,
                            message=f"artifact install failed: {e}",
                            agent_id=info.agent_id,
                        )
                    )
                    return
                # durable pre-launch record: a restarted agent rebuilds its
                # task table from these (+ the supervisor's exit_status)
                from dcos_commons_tpu.agent.daemon import serialize_check

                native_exe = ""
                if self._use_native:
                    from dcos_commons_tpu.native import task_exec_path

                    native_exe = task_exec_path()
                try:
                    # lifecycle records are per INCARNATION: a dying
                    # predecessor's exit record must never shadow the new
                    # launch.  Delivered (.done) records of other
                    # incarnations are pruned here.
                    record_dir = os.path.join(sandbox, ".super", info.task_id)
                    os.makedirs(record_dir, exist_ok=True)
                    self._prune_delivered_records(sandbox, keep=info.task_id)
                    if native_exe:
                        argv = [
                            native_exe,
                            "--sandbox", sandbox,
                            "--record-dir", record_dir,
                            "--grace", str(kill_grace_s),
                        ]
                        for rl in rlimits or []:
                            # applied by the supervisor in the child
                            # between fork and exec (setrlimit(2))
                            argv += [
                                "--rlimit",
                                f"{rl['name']}="
                                f"{rl.get('soft', -1)}:{rl.get('hard', -1)}",
                            ]
                        argv += ["--", info.command]
                        process = subprocess.Popen(
                            argv,
                            env=env,
                            start_new_session=True,
                        )
                    else:
                        process = subprocess.Popen(
                            ["/bin/sh", "-c", info.command],
                            cwd=sandbox,
                            env=env,
                            stdout=open(os.path.join(sandbox, "stdout"), "ab"),
                            stderr=open(os.path.join(sandbox, "stderr"), "ab"),
                            start_new_session=True,
                            preexec_fn=(
                                _rlimit_preexec(rlimits) if rlimits
                                else None
                            ),
                        )
                except (OSError, ValueError,
                        subprocess.SubprocessError) as e:
                    # ValueError covers preexec_fn setrlimit failures:
                    # CPython re-raises EPERM/EINVAL from the child as
                    # ValueError in the parent — it must fail THIS
                    # launch with an ERROR status, not escape into the
                    # scheduler's plan loop
                    self._pending.append(
                        TaskStatus(
                            task_id=info.task_id,
                            state=TaskState.ERROR,
                            message=f"launch failed: {e}",
                            agent_id=info.agent_id,
                        )
                    )
                    return
                # the durable record is best-effort: a failed write only
                # degrades RESTART recovery — the process is running and
                # must be tracked regardless, or it leaks untracked
                pid_identity = _proc_identity(process.pid)
                try:
                    record = {
                        "info": info.to_dict(),
                        "pid": process.pid,
                        "pid_identity": pid_identity,
                        "native": bool(native_exe),
                        "readiness": serialize_check(readiness),
                        "health": serialize_check(health),
                    }
                    with open(os.path.join(record_dir, "task.json"), "w") as f:
                        json.dump(record, f)
                except OSError:
                    pass
                self._tasks[info.task_id] = _Running(
                    info=info,
                    process=process,
                    sandbox=sandbox,
                    readiness=readiness,
                    health=health,
                    started_at=time.monotonic(),
                    pid=process.pid,
                    pid_identity=pid_identity,
                    native=bool(native_exe),
                    record_dir=record_dir,
                )
        finally:
            # unpinned staged artifacts not consumed by install_uris
            # (any aborted launch path above) must not pile up on disk
            discard_staged(staged_uris)

    def _prune_delivered_records(self, sandbox: str, keep: str) -> None:
        import shutil as _shutil

        super_root = os.path.join(sandbox, ".super")
        try:
            entries = os.listdir(super_root)
        except OSError:
            return
        for task_id in entries:
            if task_id == keep:
                continue
            record_dir = os.path.join(super_root, task_id)
            if os.path.exists(os.path.join(record_dir, "task.json.done")):
                _shutil.rmtree(record_dir, ignore_errors=True)

    def kill(self, task_id: str, grace_period_s: float = 0.0) -> None:
        with self._lock:
            running = self._tasks.get(task_id)
            if running is None:
                return
            running.kill_requested = True
            # native tasks: the supervisor owns grace escalation; the
            # Python deadline is only the lost-supervisor backstop
            margin = 10.0 if running.native else 0.0
            running.kill_deadline = (
                time.monotonic() + grace_period_s + margin
            )
            if running.native and grace_period_s > 0:
                # hand the REQUESTED grace to the supervisor (it reads
                # record_dir/grace on SIGTERM) — the launch-time --grace
                # is only the default, and e.g. pod replace may ask for
                # a different drain than the spec's kill-grace-period
                from dcos_commons_tpu.common import atomic_write_text

                try:
                    atomic_write_text(
                        os.path.join(
                            running.record_dir or running.sandbox, "grace"
                        ),
                        f"{grace_period_s}\n",
                    )
                except OSError:
                    pass  # supervisor falls back to the launch grace
            try:
                if running.native:
                    os.kill(running.pid, signal.SIGTERM)
                else:
                    os.killpg(running.pid, signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
            if running.native and grace_period_s <= 0:
                # an explicit zero grace means NOW — don't defer to the
                # supervisor's launch-time grace
                self._force_kill(running)

    def _force_kill(self, running: _Running) -> None:
        """SIGKILL the task's process group (non-native: the child IS
        the group leader; native: read the supervisor's child.pid)."""
        pid = running.pid
        if running.native:
            try:
                with open(os.path.join(
                    running.record_dir or running.sandbox, "child.pid"
                )) as f:
                    pid = int(f.read().strip())
            except (OSError, ValueError):
                pass
        try:
            os.killpg(pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass

    def active_task_ids(self) -> Set[str]:
        with self._lock:
            return set(self._tasks)

    def reconcile(self) -> None:
        """Explicit reconciliation (reference: ExplicitReconciler —
        the master re-sends CURRENT task states on request).  Status
        transitions here are edge-triggered: once poll() hands a
        RUNNING out, it is never re-reported — so a scheduler that
        died between draining poll() and acting on the batch would
        strand its successor with store-STAGING tasks whose RUNNING
        can never arrive (found by the chaos harness's
        mid-status-fan-in/mid-plan-transition kills).  A restarted
        scheduler calls this to re-arm the current state of every
        live task for the next poll; terminal fates already
        re-deliver via the durable task records."""
        with self._lock:
            for running in self._tasks.values():
                running.running_reported = False

    def poll(self) -> List[TaskStatus]:
        with self._lock:
            out = list(self._pending)
            self._pending.clear()
            for status in out:
                record_path = self._undelivered_records.pop(
                    status.task_id, None
                )
                if record_path:
                    try:
                        os.replace(record_path, record_path + ".done")
                    except OSError:
                        pass
            now = time.monotonic()
            finished: List[str] = []
            for task_id, running in self._tasks.items():
                out.extend(self._poll_one(task_id, running, now, finished))
            for task_id in finished:
                del self._tasks[task_id]
            return out

    # -- internals ----------------------------------------------------

    def _poll_one(
        self, task_id: str, running: _Running, now: float, finished: List[str]
    ) -> List[TaskStatus]:
        out: List[TaskStatus] = []
        info = running.info
        returncode = running.exit_code()
        if returncode is not None:
            finished.append(task_id)
            # fate delivered: the durable record must not be re-
            # reported by a later agent restart
            if running.record_dir:
                record = os.path.join(running.record_dir, "task.json")
                try:
                    os.replace(record, record + ".done")
                except OSError:
                    pass
            if returncode == -1 and not running.kill_requested:
                state = TaskState.LOST  # fate unknowable
            elif running.kill_requested:
                state = TaskState.KILLED
                # NOTE: an unrequested signal death (OOM killer,
                # operator SIGKILL) stays FAILED — KILLED is not a
                # failure state and would leave a deploy step wedged
            elif returncode == 0:
                state = TaskState.FINISHED
            else:
                state = TaskState.FAILED
            out.append(
                TaskStatus(
                    task_id=task_id,
                    state=state,
                    message=(
                        "supervisor lost" if returncode == -1
                        else f"exit {returncode}"
                    ),
                    agent_id=info.agent_id,
                )
            )
            return out
        if running.kill_requested and now >= running.kill_deadline:
            self._force_kill(running)
        if not running.running_reported:
            running.running_reported = True
            out.append(
                TaskStatus(
                    task_id=task_id,
                    state=TaskState.RUNNING,
                    agent_id=info.agent_id,
                    # a reconcile()-triggered re-report must carry the
                    # readiness the task already earned, or the step
                    # waits forever for a check that won't re-run
                    ready=running.readiness is None or
                    running.ready_reported,
                )
            )
        # readiness: run the check at its declared interval until it
        # passes once (a subprocess per poll per task would melt the
        # agent at fleet scale and ignore the spec's cadence)
        if running.readiness is not None and not running.ready_reported:
            if now - running.last_check_at >= running.readiness.interval_s:
                running.last_check_at = now
                if self._run_check(running, running.readiness.cmd,
                                   running.readiness.timeout_s):
                    running.ready_reported = True
                    out.append(
                        TaskStatus(
                            task_id=task_id,
                            state=TaskState.RUNNING,
                            agent_id=info.agent_id,
                            ready=True,
                            message="readiness check passed",
                        )
                    )
        # health: checking begins after delay_s AND grace_period_s,
        # then runs at the declared interval; failures accumulate ->
        # kill (reference HealthCheckSpec: delay gates the first check,
        # grace suppresses failure counting while warming)
        health = running.health
        if health is not None and \
                now - running.started_at > max(
                    health.grace_period_s, health.delay_s
                ) and \
                now - running.last_health_at >= health.interval_s:
            running.last_health_at = now
            if self._run_check(running, health.cmd, health.timeout_s):
                running.health_failures = 0
            else:
                running.health_failures += 1
                if running.health_failures >= health.max_consecutive_failures:
                    self.kill(task_id)
        return out

    def _run_check(self, running: _Running, cmd: str, timeout_s: float) -> bool:
        env = dict(os.environ)
        env.update(running.info.env)
        env["SANDBOX"] = running.sandbox
        try:
            result = subprocess.run(
                ["/bin/sh", "-c", cmd],
                cwd=running.sandbox,
                env=env,
                timeout=timeout_s,
                capture_output=True,
            )
            return result.returncode == 0
        except subprocess.TimeoutExpired:
            return False

    # -- test helpers -------------------------------------------------

    def sandbox_of(self, task_name: str) -> str:
        return os.path.join(self._workdir, task_name)

    def steplog_of(
        self, task_name: str, agent_id: Optional[str] = None
    ) -> List[dict]:
        """Worker step telemetry from the task's sandbox
        (trace/steplog.py JSONL): the scheduler's /v1/debug/trace
        merges these into the control-plane timeline so gang skew
        across hosts is visible in one view.  [] when the task never
        wrote one.  ``agent_id`` is the routing hint RemoteFleet
        needs; one sandbox tree serves every simulated host here."""
        from dcos_commons_tpu.trace.steplog import STEPLOG_NAME, read_steplog

        return read_steplog(
            os.path.join(self._workdir, task_name, STEPLOG_NAME)
        )

    def serving_stats_of(
        self, task_name: str, agent_id: Optional[str] = None
    ) -> dict:
        """Serving-load gauges from the task's sandbox (serve/engine.py
        servestats.json): queue depth, active slots, KV occupancy,
        tokens/s.  The scheduler's /v1/debug/serving merges these per
        pod — the load signal scale-out decisions read.  {} when the
        task is not a serving worker (never wrote one)."""
        from dcos_commons_tpu.serve.engine import (
            SERVESTATS_NAME,
            read_servestats,
        )

        return read_servestats(
            os.path.join(self._workdir, task_name, SERVESTATS_NAME)
        )

    def advertised_port_of(
        self, task_name: str, agent_id: Optional[str] = None
    ) -> Optional[int]:
        """The HTTP port the task actually bound (annotated into its
        servestats snapshot): /v1/endpoints advertises THIS for
        ``advertise: true`` ports — on a one-machine simulated fleet
        the reserved port may be taken, and the listing must name the
        dialable one (ISSUE 12)."""
        from dcos_commons_tpu.agent.base import Agent

        return Agent.advertised_port_of(self, task_name, agent_id)

    def shutdown(self) -> None:
        with self._lock:
            for task_id in list(self._tasks):
                self.kill(task_id)
            for running in self._tasks.values():
                if running.process is not None:
                    try:
                        running.process.wait(timeout=5)
                    except subprocess.TimeoutExpired:
                        self._force_kill(running)
                elif running.pid:
                    # recovered task: give the supervisor a moment to
                    # run its grace escalation, then force.  Polling is
                    # correct here: the pid is a FOREIGN process
                    # (adopted across an agent restart, not our child),
                    # so there is no waitable handle — kill(pid, 0) is
                    # the only portable liveness probe, and this runs
                    # once at shutdown, never in the offer/status path.
                    deadline = time.monotonic() + 5
                    while time.monotonic() < deadline and _pid_alive(
                        running.pid
                    ):
                        time.sleep(0.05)  # sdklint: disable=no-blocking-sleep — see above: no child handle to wait on
                    if _pid_alive(running.pid):
                        self._force_kill(running)
            self._tasks.clear()
