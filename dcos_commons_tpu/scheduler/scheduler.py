"""DefaultScheduler: the event loop tying every layer together.

Reference: scheduler/DefaultScheduler.java:81 + framework/
OfferProcessor.java — one cycle of ``run_cycle()`` corresponds to one
pass of the reference's offer thread (OfferProcessor.java:294-418):

    status intake  (statusUpdate fan-in,    DefaultScheduler.java:541-568)
    reconcile gate (AbstractScheduler.java:163-184)
    plan candidates -> evaluate -> WAL -> launch
                   (PlanScheduler.java:50-100 -> OfferEvaluator ->
                    PersistentLaunchRecorder, DefaultScheduler.java:423-470)
    reservation GC (unexpected resources,   DefaultScheduler.java:483-538)
    kill retries   (TaskKiller)

The loop is synchronous and steppable — the sim harness and tests call
run_cycle() directly (the reference's sim harness scripts ticks the
same way); ``run_forever`` wraps it in a thread for production.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional, Set

from dcos_commons_tpu.agent.base import Agent
from dcos_commons_tpu.common import Label, TaskStatus, task_name_of
from dcos_commons_tpu.debug.trackers import OfferOutcomeTracker
from dcos_commons_tpu.metrics.registry import Metrics
from dcos_commons_tpu.offer.evaluate import EvaluationContext, OfferEvaluator
from dcos_commons_tpu.offer.inventory import SliceInventory
from dcos_commons_tpu.offer.ledger import ReservationLedger
from dcos_commons_tpu.plan.coordinator import DefaultPlanCoordinator
from dcos_commons_tpu.plan.plan import Plan
from dcos_commons_tpu.plan.plan_manager import DefaultPlanManager, PlanManager
from dcos_commons_tpu.plan.step import ActionStep, DeploymentStep
from dcos_commons_tpu.recovery.manager import DefaultRecoveryPlanManager
from dcos_commons_tpu.runtime.reconciler import Reconciler
from dcos_commons_tpu.runtime.task_killer import TaskKiller
from dcos_commons_tpu.runtime.token_bucket import TokenBucket
from dcos_commons_tpu.specification.specs import ServiceSpec, task_full_name
from dcos_commons_tpu.state.launch_recorder import PersistentLaunchRecorder
from dcos_commons_tpu.state.state_store import (
    GoalStateOverride,
    OverrideProgress,
    StateStore,
)
from dcos_commons_tpu.trace.recorder import TraceRecorder

LOG = logging.getLogger(__name__)


class DefaultScheduler:
    def __init__(
        self,
        spec: ServiceSpec,
        state_store: StateStore,
        ledger: ReservationLedger,
        inventory: SliceInventory,
        agent: Agent,
        evaluator: OfferEvaluator,
        deploy_manager: DefaultPlanManager,
        recovery_manager: DefaultRecoveryPlanManager,
        other_managers: Optional[List[PlanManager]] = None,
        metrics: Optional[Metrics] = None,
        outcome_tracker: Optional[OfferOutcomeTracker] = None,
        config_store=None,
        framework_store=None,
        kill_orphaned_tasks: bool = True,
        revive_bucket: Optional[TokenBucket] = None,
        tracer: Optional[TraceRecorder] = None,
        journal=None,
        health_monitor=None,
        action_policy=None,
    ):
        # stores surfaced to the HTTP API (/v1/configs, /v1/state);
        # None when the scheduler is wired by hand in unit tests
        self.config_store = config_store
        self.framework_store = framework_store
        self.spec = spec
        self.state_store = state_store
        self.ledger = ledger
        self.inventory = inventory
        self.agent = agent
        self.evaluator = evaluator
        self.deploy_manager = deploy_manager
        self.recovery_manager = recovery_manager
        self.other_managers = list(other_managers or [])
        self.metrics = metrics or Metrics()
        self.outcome_tracker = outcome_tracker or OfferOutcomeTracker()
        # traceview: the bounded flight recorder every layer of one
        # offer cycle records into (trace/recorder.py).  One
        # correlation id is minted per cycle; launches register their
        # span so later status arrivals and the plan-step transitions
        # they trigger join the chain.  Surfaced at /v1/debug/trace.
        self.tracer = tracer or TraceRecorder()
        if self.tracer.metrics is None:
            self.tracer.metrics = self.metrics
        self.tracer.service = self.tracer.service or spec.name
        # correlation context of the in-flight status/launch: set under
        # _lock by the cycle's thread, tagged with that thread's id so
        # a step verb arriving on an HTTP thread (step.restart() is
        # lock-free) can never borrow an unrelated status's anchor
        self._trace_ctx: Optional[tuple] = None  # (thread_id, trace, span)
        # HA (dcos_commons_tpu/ha/): crash-injection hook for the chaos
        # harness — callable(kind) invoked at every span-boundary kind
        # (post-evaluate, post-wal, mid-status-fan-in,
        # mid-plan-transition, mid-checkpoint-prune); None in
        # production.  ha_state (election.HAState) is attached by the
        # builder/runner when a leader lease is wired; last_rehydration
        # is the first cycle's WAL-replay report.
        self.chaos = None
        self.ha_state = None
        self.last_rehydration = None
        # health plane (dcos_commons_tpu/health/): the durable event
        # journal (operator verbs, plan transitions, failovers,
        # recovery, detector alerts — persisted through the state
        # store, so HA mode fences and replays it) and the per-cycle
        # monitor (metric history sampling + anomaly detectors).
        # Surfaced at /v1/debug/health and /v1/debug/events.
        from dcos_commons_tpu.health import (
            EventJournal,
            HealthMonitor,
            StatePropertyBackend,
        )

        if journal is None:
            # adopt the monitor's journal when it brought a real (or
            # deliberately disabled) one; default to a store-backed
            # journal otherwise
            if health_monitor is not None and (
                health_monitor.journal._backend is not None
                or not health_monitor.journal.enabled
            ):
                journal = health_monitor.journal
            else:
                journal = EventJournal(StatePropertyBackend(state_store))
        self.journal = journal
        self.health = health_monitor or HealthMonitor(journal=journal)
        self.health.journal = journal
        self.health.attach(self)
        # recovery phases journal their creation (the recovery plan
        # prunes completed phases; the journal remembers them)
        recovery_manager.journal = journal
        from dcos_commons_tpu.ha.rehydrate import PlanCheckpointer

        self._plan_checkpointer = PlanCheckpointer(state_store)
        # set by nudge()/step transitions; checkpointing skips clean
        # cycles so idle heartbeats never serialize the plan tree
        self._plan_dirty = True
        self._transition_seq = 0
        # the closed health->action loop (health/actions.py): the
        # engine's dynamic `autoscale` plan joins the coordinator so
        # automated scale-out/scale-in phases ride the ordinary
        # candidate -> evaluate -> WAL -> launch machinery, are
        # operator-interruptible via the plan verbs, and are
        # checkpointed/restored across failover like every plan.
        # Policy defaults OFF; the engine still settles/reseeds
        # journal-latched actions so a disabled successor never
        # forgets a predecessor's in-flight plan.
        from dcos_commons_tpu.health.actions import HealthActionEngine

        self.actions = HealthActionEngine(policy=action_policy)
        self.other_managers.append(self.actions.manager)
        # an instance an in-flight scale action owns is the SCALE
        # phase's to drive (incl. retrying a failed scale-out
        # launch) — recovery must defer exactly as it defers to an
        # incomplete deploy step, or the two plans would trade
        # launches for the same task names
        recovery_manager.add_externally_managed(
            self._scale_managed_instance
        )
        # deploy before recovery: rollout owns incomplete pods, and the
        # recovery manager defers to them via externally_managed
        self.coordinator = DefaultPlanCoordinator(
            [deploy_manager, recovery_manager, *self.other_managers]
        )
        self.launch_recorder = PersistentLaunchRecorder(
            state_store, tracer=self.tracer
        )
        self.task_killer = TaskKiller(agent)
        self.reconciler = Reconciler(state_store, agent)
        # standalone mode sweeps agent tasks the store doesn't own
        # (lost-kill safety net); in multi-service mode the agent view
        # is SHARED, so the MultiServiceScheduler does a merged sweep
        # instead and this is disabled per service
        self.kill_orphaned_tasks = kill_orphaned_tasks
        # revive throttling: a flapping work-set (task crash-looping
        # between suppress and revive) may not hammer the inventory
        # scan every cycle (reference: rate-limited ReviveManager,
        # framework/ReviveManager.java + TokenBucket.java).  Fallback
        # tuning comes from SchedulerConfig so there is one source of
        # truth for the defaults.
        if revive_bucket is None:
            from dcos_commons_tpu.scheduler.config import SchedulerConfig

            defaults = SchedulerConfig()
            revive_bucket = TokenBucket(
                capacity=defaults.revive_capacity,
                refill_interval_s=defaults.revive_refill_s,
            )
        self.revive_bucket = revive_bucket
        # base URL of this scheduler's own API server, set by the serve
        # runner; when present agents pull config templates from
        # /v1/artifacts over HTTP (the reference bootstrap flow,
        # sdk/bootstrap/main.go:291-376); when absent (in-process
        # tests/bench) template content ships inline with the launch
        self.artifact_base: Optional[str] = None
        # security plane (X2): resolves pod secret refs at launch and
        # issues per-task TLS PEMs; values ride ONLY the launch channel
        # (never the state store or artifact URLs).  Set by the builder
        # (reference: SecretsClient + CertificateAuthorityClient)
        self.secrets_provider = None
        self.certificate_authority = None
        self._suppressed = False
        # pending-nudge flag consumed by the multi-service offer
        # discipline: a suppressed (skipped) service is revived when a
        # nudge fired since its last cycle (status arrival, HTTP verb)
        self._nudged = False
        self._fatal_error: Optional[str] = None
        self._stop = threading.Event()
        # event-driven wake-up (offer-cycle fast path): status arrival
        # and HTTP mutations set this, so run_forever cycles at event
        # speed and the interval is only a fallback heartbeat
        self._wake = threading.Event()
        self._lock = threading.RLock()
        # snapshot-cache observability, surfaced through the existing
        # /v1 metrics routes (gauges ride the Metrics snapshot)
        self.metrics.gauge(
            "offers.snapshot_cache.hit",
            lambda: float(getattr(inventory, "cache_hits", 0)),
        )
        self.metrics.gauge(
            "offers.snapshot_cache.miss",
            lambda: float(getattr(inventory, "cache_misses", 0)),
        )
        # dirty-host incremental evaluation: how many hosts the last
        # snapshot sync actually re-synthesized (0 on a quiet fleet)
        self.metrics.gauge(
            "offers.dirty_hosts",
            lambda: float(getattr(inventory, "last_dirty_hosts", 0)),
        )
        self.evaluator.metrics = self.metrics
        self.evaluator.tracer = self.tracer
        self._wire_step_tracing()
        # agents that learn of statuses asynchronously (readiness
        # monitors, test fixtures) nudge the loop instead of waiting
        # out the heartbeat
        add_listener = getattr(agent, "add_status_listener", None)
        if callable(add_listener):
            add_listener(self.nudge)

    # -- the loop -----------------------------------------------------

    def run_cycle(self, allow_footprint_growth: bool = True) -> None:
        """One pass of the event loop.  ``allow_footprint_growth=False``
        is the multi-service offer discipline: status intake, kills, GC
        and in-place relaunches proceed, but no NEW reservations are
        taken (reference: OfferDiscipline/ParallelFootprintDiscipline,
        scheduler/multi/OfferDiscipline.java:11-33)."""
        with self._lock, self.metrics.time("cycle.process"):
            # the reference's offers.process timer (Metrics.java:33):
            # scale tests fence on this staying bounded as the fleet
            # and service count grow.  The cycle span mints THE
            # correlation id: everything this cycle causes (evaluation,
            # WAL, launch — and, via the launch registry, the statuses
            # and step transitions that arrive in later cycles) shares
            # its trace id.
            with self.tracer.span("cycle", track="scheduler") as cycle:
                # recovery steps are created dynamically: (re)attach
                # the transition listener before statuses route
                self._wire_step_tracing()
                n_statuses = self._intake_statuses(cycle)
                if not self.reconciler.is_reconciled:
                    # first cycle of this scheduler incarnation: full
                    # re-hydration (plan-checkpoint restore + WAL
                    # replay against agent reality).  Cold start and
                    # failover take the same path — the only
                    # difference is what the replay finds.
                    n_statuses += self._rehydrate_locked(cycle)
                    self.metrics.incr("reconciles")
                n_candidates = self._process_candidates(
                    allow_footprint_growth, parent=cycle
                )
                self._gc_reservations()
                if self.kill_orphaned_tasks:
                    self._kill_orphans()
                self.task_killer.retry_pending()
                # first full deployment done: scheduler restarts now
                # build an *update* plan (reference: StateStoreUtils
                # deployment-completed bit read by selectDeployPlan)
                if not self.state_store.deployment_was_completed() and \
                        self.deploy_manager.get_plan().is_complete:
                    self.state_store.set_deployment_completed()
                if self._plan_dirty:
                    # persist plan runtime state (interrupts, step
                    # statuses) so a successor resumes at the exact
                    # state the operator left — the failover contract.
                    # Cleared BEFORE serializing (a racing flip costs
                    # one extra checkpoint, never a lost one) but
                    # restored on failure: a transient store error
                    # must not silently drop an operator verb's
                    # checkpoint until the next plan transition.
                    self._plan_dirty = False
                    try:
                        self._plan_checkpointer.checkpoint(
                            self.plans(), chaos=self._chaos_point
                        )
                    except BaseException:
                        self._plan_dirty = True
                        raise
                # health plane: metric-history sampling + detectors +
                # journal flush, time-throttled internally.  Runs on
                # idle heartbeats too — a serving pod burns its TTFT
                # SLO precisely while the control plane has nothing
                # to do.  Never raises (counted in observe_errors).
                self.health.observe(self)
                cycle.set_attr("statuses", n_statuses)
                cycle.set_attr("candidates", n_candidates)
                if n_statuses == 0 and n_candidates == 0:
                    # idle heartbeat: keep the bounded flight recorder
                    # for cycles that did work (busy-polls at 0.05s
                    # would otherwise evict every interesting trace)
                    cycle.drop()

    def run_forever(
        self,
        interval_s: float = 0.5,
        max_consecutive_failures: int = 5,
        busy_poll_s: float = 0.05,
    ) -> threading.Thread:
        """A transient cycle failure is logged and retried; after
        ``max_consecutive_failures`` in a row the loop declares itself
        wedged, records ``fatal_error`` and stops, so the serving
        process can exit and be restarted by its supervisor (reference:
        deliberate crash-to-restart on deadlock, SchedulerConfig.java
        DISABLE_DEADLOCK_EXIT semantics — exit is the default).

        The wait between cycles is event-driven: ``nudge()`` (status
        arrival, HTTP mutations) wakes the loop immediately, and while
        launched work awaits its statuses the wait shortens to
        ``busy_poll_s`` (poll-only agents surface transitions only
        inside a cycle).  ``interval_s`` is the idle fallback
        heartbeat, so an N-step deploy no longer pays N x interval_s
        of pure sleep."""
        def loop():
            failures = 0
            while not self._stop.is_set():
                self._wake.clear()
                try:
                    self.run_cycle()
                    failures = 0
                except Exception as exc:
                    failures += 1
                    LOG.exception(
                        "scheduler cycle failed (%d consecutive)", failures
                    )
                    if failures >= max_consecutive_failures:
                        self._fatal_error = repr(exc)
                        LOG.critical(
                            "scheduler wedged after %d consecutive cycle "
                            "failures; stopping loop for supervised restart",
                            failures,
                        )
                        self._stop.set()
                        break
                timeout = interval_s
                if self._work_in_flight():
                    timeout = min(interval_s, busy_poll_s)
                with self.metrics.time("cycle.wait"):
                    self._wake.wait(timeout)

        thread = threading.Thread(target=loop, name="scheduler-loop", daemon=True)
        thread.start()
        return thread

    def nudge(self) -> None:
        """Wake run_forever for an immediate cycle (status arrival,
        plan work made pending, HTTP mutation).  Safe from any thread;
        a nudge during a cycle makes the next wait return at once."""
        self.metrics.incr("cycle.nudges")
        # anything worth waking for may have changed plan state (HTTP
        # plan verbs mutate plan objects directly): re-checkpoint on
        # the next cycle.  Monotonic bool flip from any thread; the
        # cycle clears it BEFORE serializing, so a racing flip only
        # costs one extra checkpoint, never a lost one.
        # racecheck: handoff=monotonic dirty flip; cycle clears before serializing, a racing flip costs one extra checkpoint, never a lost one
        self._plan_dirty = True  # sdklint: disable=lock-discipline — see above
        self._nudged = True  # sdklint: disable=lock-discipline — same monotonic-flip contract
        self._wake.set()

    def take_nudge(self) -> bool:
        """Consume the pending-nudge flag (multi-service offer
        discipline): True when nudge() fired since the last consume.
        Monotonic bool flip; a racing nudge after the read costs one
        extra revive cycle, never a lost wake."""
        if self._nudged:
            self._nudged = False  # sdklint: disable=lock-discipline — see nudge()
            return True
        return False

    def work_pending(self) -> bool:
        """True while this service could need an offer cycle: pending/
        in-flight plan work, unfinished reconciliation, or unacked
        kills.  False = the service may be SUPPRESSED (skipped
        entirely by MultiServiceScheduler.run_cycle) until a status or
        nudge revives it — the reference's suppress/revive semantics
        (framework/ReviveManager.java), now load-bearing at fleet
        scale.  DELAYED (backoff) steps keep a plan incomplete, so a
        service waiting out a crash-loop backoff is never suppressed
        (backoff expiry is time-, not event-, driven)."""
        return (
            not self.reconciler.is_reconciled
            or bool(self.task_killer.pending_ids())
            or self.coordinator.has_work()
        )

    def _chaos_point(self, kind: str) -> None:
        """Crash-injection hook: the chaos harness installs a callable
        that raises at a chosen span-boundary kind, simulating a
        scheduler death at exactly that point.  No-op in production."""
        if self.chaos is not None:
            self.chaos(kind)

    # -- re-hydration (dcos_commons_tpu/ha/rehydrate.py) --------------

    def _rehydrate_locked(self, cycle) -> int:
        """First cycle of this incarnation: restore plan checkpoints
        (operator interrupts / force-completes), then replay the
        launch WAL against agent reality — adopt live tasks, re-issue
        launches the crash lost, hand unobserved deaths to recovery —
        and record it all as one ``rehydrate.replay`` span chained to
        the election.promote that created this incarnation (when one
        did).  Returns the number of synthesized statuses routed."""
        from dcos_commons_tpu.common import TaskState
        from dcos_commons_tpu.ha import rehydrate as _rehydrate

        promote_ref = (
            self.ha_state.lease.promote_ref
            if self.ha_state is not None and self.ha_state.lease is not None
            else None
        )
        kwargs = (
            {"trace_id": promote_ref[0], "parent_id": promote_ref[1]}
            if promote_ref is not None else {"parent": cycle}
        )
        # re-synthesize journal-latched in-flight health actions
        # BEFORE the checkpoint restore: their phases must exist for
        # restore_plans to re-apply operator interrupts onto them
        self.actions.seed(self)
        report = _rehydrate.RehydrationReport()
        with self.tracer.span(
            "rehydrate.replay", track="scheduler", **kwargs
        ) as span:
            _rehydrate.restore_plans(
                self.state_store, self.plans(), report
            )
            _rehydrate.scan_double_reservations(self.ledger, report)
            stored = self.state_store.fetch_statuses()
            stored_ids = {s.task_id for s in stored.values()}
            active = self.agent.active_task_ids()
            report.adopted = sum(
                1 for s in stored.values()
                if not s.state.is_terminal and s.task_id in active
            )
            report.orphans = len(active - stored_ids)
            n = 0
            for status in self.reconciler.reconcile():
                try:
                    prev = stored.get(task_name_of(status.task_id))
                except ValueError:
                    prev = None
                if prev is not None and prev.state is TaskState.STAGING:
                    # the WAL seed never progressed and no agent knows
                    # the task: the crash landed between WAL and
                    # launch.  The LOST status re-pends the step; the
                    # evaluator relaunches in place on the committed
                    # reservations.
                    report.reissued += 1
                else:
                    report.lost += 1
                self._process_status(status, parent=span)
                n += 1
            for attr in ("adopted", "reissued", "lost", "orphans",
                         "restored_plans", "restored_steps",
                         "double_reservations"):
                span.set_attr(attr, getattr(report, attr))
        self.last_rehydration = report.to_dict()
        if self.ha_state is not None:
            self.ha_state.note_rehydration(self.last_rehydration)
        for key in ("adopted", "reissued", "lost"):
            value = getattr(report, key)
            if value:
                self.metrics.incr(f"ha.rehydrate.{key}", value)
        # journal the incarnation boundary: a failover (promotion at a
        # new lease epoch) or a cold start, with the replay verdict —
        # the journal survives the takeover, so the successor's first
        # event explains what it inherited
        lease = self.ha_state.lease if self.ha_state is not None else None
        self.journal.append(
            "election" if lease is not None else "recovery",
            message=(
                f"rehydrated: adopted={report.adopted} "
                f"reissued={report.reissued} lost={report.lost} "
                f"orphans={report.orphans}"
            ),
            adopted=report.adopted,
            reissued=report.reissued,
            lost=report.lost,
            orphans=report.orphans,
            epoch=lease.epoch if lease is not None else None,
        )
        return n

    def _work_in_flight(self) -> bool:
        """True while any plan step holds launched-but-unconfirmed
        work (PREPARED/STARTING) — the statuses that complete it are
        only observable by polling the agent inside a cycle."""
        return any(
            manager.in_progress_assets()
            for manager in self.coordinator.plan_managers
        )

    @property
    def fatal_error(self) -> Optional[str]:
        """Non-None once run_forever gave up; surfaced via /v1/health
        and the serve entrypoint's exit code."""
        return self._fatal_error

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()  # release a loop parked in its fallback wait

    # -- status intake ------------------------------------------------

    def _intake_statuses(self, parent=None) -> int:
        n = 0
        for status in self.agent.poll():
            self._process_status(status, parent=parent)
            n += 1
        return n

    def _process_status(self, status: TaskStatus, parent=None) -> None:
        """Reference: DefaultScheduler.processStatusUpdate (:541-568)."""
        self.metrics.incr(f"task_status.{status.state.value}")
        try:
            task_name = task_name_of(status.task_id)
        except ValueError:
            LOG.warning("unparseable task id %s", status.task_id)
            return
        # status span, linked to its LAUNCH span via the task id so the
        # chain survives across cycles; an unknown launch (pre-restart
        # task, reconciled orphan) anchors to the current cycle instead
        ref = self.tracer.launch_ref(status.task_id)
        event = self.tracer.event(
            f"status:{status.state.value}",
            parent=None if ref else parent,
            trace_id=ref.trace_id if ref else 0,
            parent_id=ref.span_id if ref else 0,
            track=ref.track if ref else "scheduler",
            task=task_name,
            task_id=status.task_id,
            **({"message": status.message} if status.message else {}),
        )
        stored = self.state_store.store_status(task_name, status)
        if not stored:
            event.attrs["stale"] = "true"
            LOG.info("dropped stale status %s for %s",
                     status.state.value, task_name)
            return
        # chaos: status persisted but NOT yet routed to the plans — a
        # successor must converge from the stored status alone
        self._chaos_point("mid-status-fan-in")
        # a pause/resume override completes once the task relaunched
        # UNDER the override (progress IN_PROGRESS, set at launch time)
        # reaches RUNNING; a RUNNING from the pre-override task arrives
        # while progress is still PENDING and must not complete it
        # (reference: GoalStateOverride progress machine)
        if status.state.is_running:
            override, progress = self.state_store.fetch_goal_override(task_name)
            if progress is OverrideProgress.IN_PROGRESS:
                self.state_store.store_goal_override(
                    task_name, override, OverrideProgress.COMPLETE
                )
        self.task_killer.handle_status(status)
        # step transitions triggered by THIS status reference its
        # correlation id (the listener reads _trace_ctx)
        # racecheck: handoff=thread-id-stamped slot; _on_step_transition only honors a ctx whose get_ident matches its own, so a concurrent writer's value is ignored, worst case an unanchored span
        self._trace_ctx = (
            threading.get_ident(), event.trace_id, event.span_id
        )
        seq_before = self._transition_seq
        try:
            for manager in self.coordinator.plan_managers:
                manager.update(status)
        finally:
            self._trace_ctx = None
        if self._transition_seq != seq_before:
            # chaos: the status moved a plan step, but the cycle's
            # post-transition work (deployment-completed flip, plan
            # checkpoint) never ran — a successor must not re-run the
            # transitioned step
            self._chaos_point("mid-plan-transition")

    def _wire_step_tracing(self) -> None:
        """Attach the step-transition listener to every plan step that
        exists right now (recovery steps are created dynamically, so
        run_cycle re-wires each pass before routing statuses)."""
        for manager in self.coordinator.plan_managers:
            set_listener = getattr(manager, "set_transition_listener", None)
            if callable(set_listener):
                set_listener(self._on_step_transition)

    def _on_step_transition(self, step, old, new, status=None) -> None:
        """Record a plan-step state transition as an instantaneous
        span.  Anchored to the in-flight status/launch correlation when
        one is active AND this is the thread that set it; operator
        verbs firing from HTTP threads record unanchored (they were
        not caused by the status the cycle thread is processing)."""
        self._transition_seq += 1
        # same monotonic-flip contract as nudge(): operator verbs fire
        # transitions from HTTP threads without the scheduler lock
        self._plan_dirty = True  # sdklint: disable=lock-discipline — see nudge()
        ctx = self._trace_ctx
        if ctx is not None and ctx[0] == threading.get_ident():
            trace_id, parent_id = ctx[1], ctx[2]
        else:
            trace_id, parent_id = 0, 0
        self.tracer.event(
            f"step:{step.name}",
            trace_id=trace_id,
            parent_id=parent_id,
            track="plan",
            **{"from": old.value, "to": new.value},
        )
        # the journal keeps step transitions AFTER the flight
        # recorder's ring has evicted them (flushed by the next
        # cycle's health pass — transitions fire inside cycles and
        # from HTTP verb threads, neither of which should pay a
        # store write per step)
        # racecheck: handoff=EventJournal.append takes its own internal lock; the attribute itself is bound once in __init__
        self.journal.append(  # sdklint: disable=lock-discipline — EventJournal serializes internally; like the tracer, it is callable from any thread
            "plan", step=step.name,
            **{"from": old.value, "to": new.value},
        )

    # -- candidates -> launches ---------------------------------------

    def _process_candidates(
        self, allow_footprint_growth: bool = True, parent=None
    ) -> int:
        candidates = self.coordinator.get_candidates()
        if not candidates:
            if not self._suppressed:
                # racecheck: handoff=only the cycle thread reaches _process_candidates (run_forever's loop, or a test driving run_cycle inline); cycles never overlap
                self._suppressed = True
                self.metrics.incr("suppresses")
            return 0
        if self._suppressed:
            # new work while suppressed: revive, rate-limited so a
            # crash-looping task can't force a full rescan every cycle
            if not self.revive_bucket.try_acquire():
                self.metrics.incr("revives.throttled")
                return 0
            self._suppressed = False
            self.metrics.incr("revives")
        # one shared evaluation context for the whole cycle: the task
        # scan and hosts dict are computed once, not once per step
        context = EvaluationContext(self.state_store, self.inventory)
        for step in candidates:
            if isinstance(step, ActionStep):
                # scheduler-side work (decommission/uninstall/custom)
                step.execute(self)
                # it may have killed/erased tasks: the shared context
                # must not serve the pre-action scan to later steps,
                # and memoized requirement outcomes computed against
                # the pre-action task set are void too
                context.invalidate_tasks()
                self.evaluator.invalidate_memo()
                continue
            if not isinstance(step, DeploymentStep):
                continue
            requirement = step.start()
            if requirement is None:
                continue
            if not allow_footprint_growth and \
                    not self._has_full_footprint(requirement):
                continue  # needs new reservations: wait for selection
            with self.metrics.time("cycle.evaluate"):
                result = self.evaluator.evaluate(
                    requirement, self.inventory, context,
                    trace_parent=parent,
                )
            self.outcome_tracker.record(requirement.name, result.outcome)
            self.metrics.incr("offers.evaluated")
            if not result.passed:
                step.update_offer_status(False)
                self.metrics.incr("offers.declined")
                continue
            # chaos: evaluation passed but NOTHING is persisted yet —
            # a successor re-evaluates from scratch, nothing leaks
            self._chaos_point("post-evaluate")
            self._kill_previous_launches(result.task_infos)
            with self.tracer.span(
                f"launch:{requirement.name}", parent=parent,
                track="scheduler",
                task_ids=",".join(t.task_id for t in result.task_infos),
            ) as launch_span:
                # WAL discipline: reservations + task infos are durable
                # BEFORE the agent sees a launch
                # (DefaultScheduler.java:454)
                # durcheck: dur-effect-before-wal=the preceding kill is recovery-covered: a crash here leaves a terminal status the successor relaunches from; this WAL only covers the NEW launch
                self.ledger.commit(result.reservations)
                self.launch_recorder.record(
                    result.task_infos, parent=launch_span
                )
                context.note_launched(result.task_infos)
                for info in result.task_infos:
                    override, progress = self.state_store.fetch_goal_override(
                        info.name
                    )
                    if progress is OverrideProgress.PENDING:
                        self.state_store.store_goal_override(
                            info.name, override, OverrideProgress.IN_PROGRESS
                        )
                    # statuses for these ids — however many cycles
                    # later — join this launch's correlation chain
                    self.tracer.register_launch(
                        info.task_id, launch_span,
                        track=f"{info.pod_type}-{info.pod_index}",
                    )
                # the PENDING->STARTING transition is launch-caused:
                # anchor it to the launch span, not a status
                self._trace_ctx = (threading.get_ident(),
                                   launch_span.trace_id,
                                   launch_span.span_id)
                try:
                    step.record_launch(
                        {t.name: t.task_id for t in result.task_infos}
                    )
                finally:
                    self._trace_ctx = None
                # chaos: reservations + WAL are durable but the agent
                # never hears about the launch — a successor must
                # re-issue it (the STAGING seed reconciles to LOST)
                self._chaos_point("post-wal")
                self._launch(result.task_infos, requirement)
            self.metrics.incr("operations.launch", len(result.task_infos))
        return len(candidates)

    def _has_full_footprint(self, requirement) -> bool:
        """True when every task of the requirement already holds
        committed reservations (an in-place relaunch, not growth)."""
        return all(
            self.ledger.for_task(name) for name in requirement.task_names()
        )

    def _kill_previous_launches(self, task_infos) -> None:
        """A relaunch of task name N must kill N's previous process
        before the new one starts (rolling update / recovery path).

        The previous launch is identified by the task id recorded in
        THIS service's own state store — never by an agent-wide name
        scan, which in multi-service mode would kill another service's
        same-named task (reference: prior task id read from the pod's
        own state store via PersistentLaunchRecorder/StateStore)."""
        for info in task_infos:
            prev = self.state_store.fetch_task(info.name)
            if prev is None or prev.task_id == info.task_id:
                continue
            status = self.state_store.fetch_status(info.name)
            if status is not None and status.task_id == prev.task_id \
                    and status.state.is_terminal:
                continue  # previous launch already dead
            self.task_killer.kill(prev.task_id)

    def _launch(self, task_infos, requirement) -> None:
        pod = requirement.pod
        for info in task_infos:
            task_spec = None
            for spec in pod.tasks:
                # exact-name match: suffix matching would confuse task
                # names that are dash-suffixes of each other
                if task_full_name(pod.type, info.pod_index, spec.name) == \
                        info.name:
                    task_spec = spec
                    break
            # paused tasks run an idle command: their readiness/health
            # checks would probe a server that isn't there
            paused = info.labels.get(Label.GOAL_STATE_OVERRIDE) == \
                GoalStateOverride.PAUSED.value
            launch_one = getattr(self.agent, "launch_one", None)
            if launch_one is not None and task_spec is not None:
                files, secret_env = self._security_payload(
                    info, pod, task_spec
                )
                kwargs = {}
                if files or secret_env:
                    kwargs = {"files": files, "secret_env": secret_env}
                if task_spec.uris:
                    # artifact entries ride the launch request; the
                    # agent fetches before the command runs (reference:
                    # Mesos fetcher on TaskInfo URIs,
                    # YAMLToInternalMappers.java:397)
                    kwargs["uris"] = [
                        {
                            "uri": u.uri,
                            "dest": u.effective_dest(),
                            "sha256": u.sha256,
                            "extract": u.extract,
                            "executable": u.executable,
                        }
                        for u in task_spec.uris
                    ]
                if pod.rlimits:
                    # the agent applies these via setrlimit(2) in the
                    # task's exec path (reference: RLimitSpec ->
                    # Mesos RLimitInfo on the ContainerInfo)
                    kwargs["rlimits"] = [
                        {"name": r.name, "soft": r.soft, "hard": r.hard}
                        for r in pod.rlimits
                    ]
                launch_one(
                    info,
                    readiness=None if paused else task_spec.readiness_check,
                    health=None if paused else task_spec.health_check,
                    templates=self._templates_for(info, task_spec),
                    kill_grace_s=task_spec.kill_grace_period_s,
                    **kwargs,
                )
            else:
                self.agent.launch([info])

    def _security_payload(self, info, pod, task_spec):
        """Secret files/env + TLS PEMs for one launch.

        Reference: TLSEvaluationStage.java placing cert/key artifacts
        and the Mesos Secret volume flow — values resolve at launch
        and ship with the request; a missing secret fails the launch
        as an ERROR file entry (the agent refuses to start the task),
        matching the fail-before-cmd bootstrap discipline.
        """
        import base64 as _b64

        files: List[dict] = []
        secret_env: Dict[str, str] = {}

        def add_file(dest: str, content: bytes, mode: int = 0o600) -> None:
            files.append({
                "dest": dest,
                "content": _b64.b64encode(content).decode(),
                "mode": mode,
            })

        for sec in pod.secrets:
            try:
                if self.secrets_provider is None:
                    raise RuntimeError("no secrets provider configured")
                value = self.secrets_provider.fetch(sec.secret)
            except Exception as e:
                files.append({
                    "dest": sec.file or sec.secret,
                    "error": f"secret {sec.secret!r} unavailable: {e}",
                })
                continue
            if sec.file:
                add_file(sec.file, value)
            env_key = sec.effective_env_key()
            if env_key:
                secret_env[env_key] = value.decode("utf-8", "replace")
        tls_specs = [
            t for t in task_spec.transport_encryption
            if t.type in ("TLS", "KEYSTORE")
        ]
        if tls_specs:
            ca = self.certificate_authority
            if ca is None:
                files.append({
                    "dest": f"{tls_specs[0].name}.crt",
                    "error": "transport-encryption requested but the "
                             "scheduler has no certificate authority",
                })
            else:
                hostname = info.labels.get(Label.HOSTNAME, "")
                for te in tls_specs:
                    cert, key = ca.issue(
                        info.name, sans=[info.name, hostname]
                    )
                    add_file(f"{te.name}.crt", cert, 0o644)
                    add_file(f"{te.name}.key", key, 0o600)
                    add_file(f"{te.name}.ca", ca.ca_cert_pem, 0o644)
        return files, secret_env

    def _templates_for(self, info, task_spec) -> List[dict]:
        """Config templates for the agent to render into the sandbox.

        URL mode (serve): the agent pulls from this scheduler's
        /v1/artifacts endpoint, pinned to the task's target config id
        so a mid-rollout task renders ITS config version (reference:
        ArtifactResource.java:50 path carries the config UUID).
        Inline mode: template text is read here and shipped with the
        launch request."""
        import os as _os

        out: List[dict] = []
        for template_path, dest in task_spec.config_templates:
            name = _os.path.basename(template_path)
            entry: dict = {"name": name, "dest": dest}
            if self.artifact_base:
                target = info.labels.get(Label.TARGET_CONFIG, "")
                entry["url"] = (
                    f"{self.artifact_base}/v1/artifacts/template/"
                    f"{target}/{info.pod_type}/{task_spec.name}/{name}"
                )
            else:
                try:
                    with open(template_path, "r") as f:
                        entry["content"] = f.read()
                except OSError as e:
                    # ship the failure to the agent: the task must
                    # ERROR rather than run with a missing config
                    entry["error"] = f"unreadable template: {e}"
            out.append(entry)
        return out

    def _kill_orphans(self) -> None:
        """Kill agent tasks this service's store does not own — either
        an unknown name or a stale id for a known name (a lost kill
        whose successor already launched).  Reference: kill-unneeded-
        tasks on register, DefaultScheduler.java:252-270.  The launch
        WAL runs before the agent launch, so a freshly-launched task is
        always store-known and never swept."""
        for task_id in self.agent.active_task_ids():
            try:
                name = task_name_of(task_id)
            except ValueError:
                self.task_killer.kill(task_id)
                continue
            info = self.state_store.fetch_task(name)
            if info is None or info.task_id != task_id:
                self.task_killer.kill(task_id)
                self.metrics.incr("operations.kill_orphan")

    # -- reservation GC ----------------------------------------------

    def _gc_reservations(self) -> None:
        """Reference: unexpected-resource cleanup
        (DefaultScheduler.java:483-538): any reservation no stored
        TaskInfo references is released."""
        expected: Set[str] = set()
        for info in self.state_store.fetch_tasks():
            expected |= set(info.resource_ids)
        for reservation in self.ledger.all():
            if reservation.reservation_id not in expected:
                self.ledger.release(reservation.reservation_id)
                self.metrics.incr("operations.unreserve")

    # -- operator verbs (wired to HTTP in http/) ----------------------

    def restart_pod(self, pod_type: str, index: int, replace: bool = False) -> List[str]:
        """Reference: PodQueries.restart (:263) — ``replace`` marks
        tasks permanently failed (pod replace), otherwise a plain
        restart (kill; recovery relaunches in place).

        Takes the scheduler lock: operator verbs arrive on HTTP server
        threads and must serialize with run_cycle so kills/overrides
        never interleave with an in-flight evaluation."""
        with self._lock:
            pod = self.spec.pod(pod_type)
            indices = list(range(pod.count)) if pod.gang else [index]
            killed = []
            for i in indices:
                for task_spec in pod.tasks:
                    full = task_full_name(pod_type, i, task_spec.name)
                    info = self.state_store.fetch_task(full)
                    if info is None:
                        continue
                    if replace:
                        self.state_store.store_tasks(
                            [info.with_label(Label.PERMANENTLY_FAILED, "true")]
                        )
                    self.task_killer.kill(
                        info.task_id, task_spec.kill_grace_period_s
                    )
                    killed.append(full)
            self.journal.append(
                "operator",
                verb="replace" if replace else "restart",
                pod=f"{pod_type}-{index}",
                tasks=len(killed),
            )
            self.nudge()  # recovery work just became pending
            return killed

    def pause_pod(
        self, pod_type: str, index: int, tasks: Optional[List[str]] = None
    ) -> List[str]:
        """Reference: PodQueries pause (:183-203) — store a PAUSED goal
        override and kill the tasks; recovery relaunches them with the
        idle override command on their existing reservations."""
        return self._override_pod(
            pod_type, index, tasks, GoalStateOverride.PAUSED
        )

    def resume_pod(
        self, pod_type: str, index: int, tasks: Optional[List[str]] = None
    ) -> List[str]:
        """Reference: PodQueries resume — clear the override and kill;
        the relaunch restores the real command."""
        return self._override_pod(
            pod_type, index, tasks, GoalStateOverride.NONE
        )

    def _override_pod(
        self,
        pod_type: str,
        index: int,
        tasks: Optional[List[str]],
        override: GoalStateOverride,
    ) -> List[str]:
        # serialized with run_cycle (see restart_pod): otherwise the
        # PENDING->IN_PROGRESS flip can attach to a relaunch that was
        # evaluated with the real (non-override) command
        with self._lock:
            pod = self.spec.pod(pod_type)
            indices = list(range(pod.count)) if pod.gang else [index]
            touched = []
            for i in indices:
                for task_spec in pod.tasks:
                    if tasks and task_spec.name not in tasks:
                        continue
                    full = task_full_name(pod_type, i, task_spec.name)
                    current, _progress = self.state_store.fetch_goal_override(
                        full
                    )
                    if current is override:
                        # no-op transition (pause of a paused task,
                        # resume of a running one): don't kill anything
                        continue
                    self.state_store.store_goal_override(
                        full, override, OverrideProgress.PENDING
                    )
                    touched.append(full)
                    info = self.state_store.fetch_task(full)
                    if info is not None:
                        self.task_killer.kill(
                            info.task_id, task_spec.kill_grace_period_s
                        )
            if touched:
                self.journal.append(
                    "operator",
                    verb="pause" if override is GoalStateOverride.PAUSED
                    else "resume",
                    pod=f"{pod_type}-{index}",
                    tasks=len(touched),
                )
            self.nudge()  # override relaunch work just became pending
            return touched

    # -- instance-count + scale verbs (ISSUE 15: the health loop) -----

    def _scale_managed_instance(self, asset: str) -> bool:
        """True while an incomplete autoscale phase step owns this
        pod-instance asset (recovery's externally-managed check)."""
        for phase in self.actions.manager.get_plan().phases:
            for step in phase.steps:
                if asset in step.get_asset_names() and \
                        not step.is_complete:
                    return True
        return False

    def set_pod_count(self, pod_type: str, count: int,
                      source: str = "operator") -> bool:
        """THE one mutation point for a non-gang pod's instance count:
        swaps the live spec (frozen dataclasses — a replaced copy),
        keeps the recovery manager's spec in step, persists the
        desired count as a state-store property so a restart/failover
        rebuilds the deploy plan at the scaled width, and journals.
        Idempotent at the target count (returns False) — what lets
        the autoscale grow/shrink steps re-run safely after a
        failover.  Action code (health/actions.py) mutates counts
        ONLY through this verb (the health-plan-only lint rule)."""
        import dataclasses

        from dcos_commons_tpu.health.actions import COUNT_PROPERTY_PREFIX

        with self._lock:
            pod = self.spec.pod(pod_type)
            count = int(count)
            if pod.gang:
                raise ValueError(
                    f"pod {pod_type!r} is a gang: its count is the "
                    "mesh width (elastic re-slicing owns gang width)"
                )
            if count < 1:
                raise ValueError("count must be >= 1")
            if count == pod.count:
                return False
            new_pod = dataclasses.replace(pod, count=count)
            self.spec = dataclasses.replace(
                self.spec,
                pods=tuple(
                    new_pod if p.type == pod_type else p
                    for p in self.spec.pods
                ),
            )
            self.recovery_manager.set_spec(self.spec)
            # the property carries the YAML floor it was written
            # against ("count@floor"): a later config update that
            # CHANGES the YAML count invalidates the override at the
            # next rebuild — operator intent in the spec always beats
            # a stale autoscale decision
            floor = self.actions._baseline(self, pod_type)
            self.state_store.store_property(
                f"{COUNT_PROPERTY_PREFIX}{pod_type}",
                f"{count}@{floor}".encode("utf-8"),
            )
            self.journal.append(
                "health" if source == "autoscale" else "operator",
                verb="set-count", pod=pod_type, count=count,
                source=source,
            )
            self.nudge()
            return True

    def scale_pod(self, pod_type: str, count: int):
        """Operator ``POST /v1/pod/<type>/scale``: manual scale
        through the SAME plan machinery (and single-flight rule) as
        the automated loop — the returned phase is visible and
        interruptible under the ``autoscale`` plan.  Serialized with
        run_cycle like every verb."""
        with self._lock:
            return self.actions.request_scale(self, pod_type, count)

    def abandon_scale(self, pod_type: str) -> bool:
        """Operator ``POST /v1/pod/<type>/scale/abandon``: drop the
        pod's in-flight scale action, reconciling the persisted count
        to deployed reality (a half-deployed widening must not resume
        at the next restart) and latching the direction's cooldown.
        The bail-out for a wedged scale action — plan interrupt only
        PARKS it (single flight then blocks the pod forever), and
        force-complete would journal a false completion."""
        with self._lock:
            return self.actions.abandon(self, pod_type)

    def draining_instances(self) -> Set[str]:
        """Pod-instance names an ACTIVE teardown plan is about to
        kill (surplus decommission or autoscale scale-in): endpoint
        assembly flips their backend rows to ``draining:true`` so the
        router stops placing BEFORE the kill step fires, while task
        and host still look perfectly healthy."""
        out: Set[str] = set()
        for plan in self.plans().values():
            for phase in plan.phases:
                targets = getattr(phase, "decommission_targets", None)
                if targets and not phase.is_complete:
                    out |= set(targets)
        return out

    # -- host lifecycle verbs (ISSUE 13: preemption & maintenance) ----

    def drain_host(self, host_id: str, window_s: float = 0.0) -> bool:
        """Operator ``POST /v1/hosts/<id>/drain``: mark the host for
        maintenance.  Placement excludes it immediately (hard
        exclusion at admission); running work keeps running (soft
        drain) and the /v1/endpoints backend rows surface it as
        ``draining`` so the serving front door stops routing new
        requests BEFORE anything is killed.  ``window_s`` > 0 records
        when the window ends — the elastic-resize rule prefers
        waiting out a finite window over shrinking a gang."""
        import time as _time

        with self._lock:
            window_end = _time.time() + window_s if window_s > 0 else 0.0
            changed = self.inventory.set_maintenance(host_id, window_end)
            if changed:
                self.journal.append(
                    "host", verb="drain", host=host_id,
                    window_s=window_s,
                    message=f"host {host_id} entering maintenance"
                            + (f" ({window_s:.0f}s window)"
                               if window_s > 0 else ""),
                )
            self.nudge()
            return changed

    def undrain_host(self, host_id: str) -> bool:
        """Operator ``POST /v1/hosts/<id>/up``: clear every
        preempted/maintenance/down mark and return the host to full
        placement eligibility."""
        with self._lock:
            changed = self.inventory.clear_host_state(host_id)
            if changed:
                self.journal.append(
                    "host", verb="up", host=host_id,
                    message=f"host {host_id} back in service",
                )
            self.nudge()
            return changed

    def preempt_host(self, host_id: str) -> List[str]:
        """Operator ``POST /v1/hosts/<id>/preempt`` (or the agent
        plane's preemption notice): the cloud took the host back.
        Marks it preempted in the inventory and surfaces the loss to
        THIS service's tasks — see :meth:`note_host_preempted`."""
        with self._lock:
            self.inventory.set_preempted(host_id)
            return self.note_host_preempted(host_id)

    def note_host_preempted(self, host_id: str) -> List[str]:
        """Every stored task on the preempted host is dead NOW and the
        capacity is not coming back: stamp PERMANENTLY_FAILED (so
        recovery goes straight to PERMANENT — for a gang member, the
        gang recovery plan) and route a synthesized TASK_LOST through
        the normal status path.  Idempotent: already-terminal tasks
        are skipped, so a verb racing the agent plane's own
        down-detection stamps each task once."""
        from dcos_commons_tpu.common import TaskState

        with self._lock:
            touched: List[str] = []
            for info in self.state_store.fetch_tasks():
                if info.agent_id != host_id:
                    continue
                status = self.state_store.fetch_status(info.name)
                if status is not None and status.task_id == info.task_id \
                        and status.state.is_terminal:
                    continue
                self.state_store.store_tasks(
                    [info.with_label(Label.PERMANENTLY_FAILED, "true")]
                )
                self._process_status(TaskStatus(
                    task_id=info.task_id,
                    state=TaskState.LOST,
                    agent_id=host_id,
                    message=f"host {host_id} preempted",
                ))
                touched.append(info.name)
            self.journal.append(
                "host", verb="preempt", host=host_id, tasks=len(touched),
                message=f"host {host_id} preempted "
                        f"({len(touched)} task(s) lost)",
            )
            self.nudge()  # gang recovery work just became pending
            return touched

    def plans(self) -> Dict[str, Plan]:
        out = {}
        for manager in self.coordinator.plan_managers:
            plan = manager.get_plan()
            out[plan.name] = plan
        return out

    def plan(self, name: str) -> Optional[Plan]:
        return self.plans().get(name)
