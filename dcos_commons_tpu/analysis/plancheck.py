"""plancheck: a bounded explicit-state model checker for the plan tree.

The reference SDK's ``plan/`` layer was hand-audited Java reviewed for
years; this rebuild's scheduler trusts the same state machines under
REORDERED status arrivals, operator verbs racing deploys, and gang
recovery restarts.  Unit tests sample a handful of interleavings;
this module explores ALL of them, bounded: it drives the *real*
``Status``/``Step``/``Phase``/``Plan``/``Strategy`` objects (no
abstract model to drift out of sync) through exhaustive breadth-first
search over an event alphabet of

- task status arrivals (RUNNING / FINISHED / FAILED / ERROR, plus a
  stale status from a dead launch),
- step launches (candidate -> ``start()`` -> ``record_launch``),
- operator verbs (restart, force-complete, interrupt, proceed at
  step / phase / plan level),

deduplicating by a canonical snapshot of every mutable field, so the
search visits each reachable *state* once (10^4–10^5 states per
configuration).  BFS order means every reported violation comes with
a MINIMAL event trace from the initial state.

Invariants checked (see docs/developer-guide.md §9 for how to add
one):

- ``no-silent-regression``: a COMPLETE step only leaves COMPLETE via
  an explicit restart verb.
- ``error-absorbs``: an ERROR step stays ERROR until an operator
  restart/force-complete.
- ``aggregate-consistent``: ``status.aggregate`` is permutation-
  insensitive over every child multiset the search actually reaches,
  ERROR dominates, and all-COMPLETE <=> COMPLETE.
- ``dependency-honored``: a DependencyStrategy phase never emits a
  candidate whose dependency is not COMPLETE.
- ``interrupt-visible``: an interrupted (WAITING) child is never
  hidden behind IN_PROGRESS/PENDING at the parent while incomplete.
- ``no-livelock``: every reachable state can still reach a
  plan-COMPLETE state (checked on the full reachability graph, so
  only sound when the exploration wasn't truncated by the cap).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from dcos_commons_tpu.common import TaskState, TaskStatus
from dcos_commons_tpu.plan.backoff import Backoff
from dcos_commons_tpu.plan.phase import Phase
from dcos_commons_tpu.plan.plan import Plan
from dcos_commons_tpu.plan.status import Status, aggregate
from dcos_commons_tpu.plan.step import (
    ActionStep,
    DeploymentStep,
    PodInstanceRequirement,
)
from dcos_commons_tpu.plan.strategy import (
    CanaryStrategy,
    DependencyStrategy,
    ParallelStrategy,
    SerialStrategy,
)
from dcos_commons_tpu.specification.specs import GoalState, PodSpec, TaskSpec

# deterministic task-id scheme: the model always launches the same id
# per step, and delivers stale statuses under a distinct dead id
_LIVE = "live"
_STALE = "stale"
_FAR_FUTURE = float("inf")


class ModelBackoff(Backoff):
    """DELAYED that never expires on its own: the checker explores the
    backoff branch symbolically (restart/force-complete are the exits)
    instead of racing the wall clock."""

    def next_delay(self, key: str) -> float:
        return _FAR_FUTURE

    def clear(self, key: str) -> None:
        pass

    def current_delay(self, key: str) -> float:
        return 0.0


# -- snapshots --------------------------------------------------------------


def _snap_step(step, quotient: bool = False) -> tuple:
    if isinstance(step, ActionStep):
        # scheduler-side steps (the gang recovery choreography's
        # kill/unreserve/trim): three mutable fields, no launch residue
        return ("A", step._status.value, step._interrupted,
                tuple(step.errors))
    if quotient and step._status is Status.COMPLETE and not step.errors:
        # quotient: a COMPLETE step ignores every status (the
        # is_complete guard) and every exit (restart) wipes the
        # residue, so the _expected/_task_states left behind by
        # force-complete are behaviorally dead — collapsing them cuts
        # the state space several-fold without losing any behavior.
        # NOT assumed: _quotient_probe() verifies the guard actually
        # holds for these step objects before the quotient is enabled,
        # so a step class that DOES react to post-COMPLETE statuses
        # falls back to exhaustive snapshots and the regression is
        # caught, not hidden.
        return (Status.COMPLETE.value, step._interrupted)
    return (
        step._status.value,
        step._interrupted,
        tuple(sorted(step._expected.items())),
        tuple(sorted((k, v.value) for k, v in step._task_states.items())),
        tuple(sorted(step._task_ready.items())),
        # canonical: the exact deadline is wall-clock noise — only
        # "parked in backoff" vs "free" distinguishes behaviors
        step._status is Status.DELAYED and step._delay_until > 0,
        tuple(step.errors),
    )


def _restore_step(step, snap: tuple) -> None:
    if isinstance(step, ActionStep):
        _tag, status, interrupted, errors = snap
        step._status = Status(status)
        step._interrupted = interrupted
        step.errors[:] = list(errors)
        return
    if len(snap) == 2:  # the COMPLETE quotient
        step._status = Status.COMPLETE
        step._interrupted = snap[1]
        step._expected = {}
        step._task_states = {}
        step._task_ready = {}
        step._delay_until = 0.0
        step.errors.clear()
        return
    (status, interrupted, expected, states, ready, delayed,
     errors) = snap
    step._status = Status(status)
    step._interrupted = interrupted
    step._expected = dict(expected)
    step._task_states = {k: TaskState(v) for k, v in states}
    step._task_ready = dict(ready)
    step._delay_until = _FAR_FUTURE if delayed else 0.0
    step.errors[:] = list(errors)


def _snap_strategy(strategy) -> tuple:
    if isinstance(strategy, CanaryStrategy):
        return (strategy._interrupted, strategy._proceeds)
    return (strategy._interrupted,)


def _restore_strategy(strategy, snap: tuple) -> None:
    if isinstance(strategy, CanaryStrategy):
        strategy._interrupted, strategy._proceeds = snap
    else:
        (strategy._interrupted,) = snap


class PlanHarness:
    """One plan instance + snapshot/restore + the event alphabet.

    ``step_interrupts`` adds per-step interrupt/proceed verbs (doubles
    each step's state space — worth it in one small configuration, not
    in all of them; phase/plan interrupts are always in the alphabet).
    """

    def __init__(self, plan: Plan, step_interrupts: bool = False,
                 world=None):
        self.plan = plan
        self.step_interrupts = step_interrupts
        self.quotient = False  # enabled by _quotient_probe() only
        # optional non-plan model state (the gang-recovery config):
        # snapshot/restore ride the plan's; ``world.events(harness)``
        # joins the alphabet; ``world.launch_overrides`` maps step
        # name -> replacement launch callable (a launch with a WAL
        # side effect the model must observe)
        self.world = world
        self.steps: List[DeploymentStep] = [
            s for p in plan.phases for s in p.steps
        ]
        self.strategies = [plan.strategy] + [
            p.strategy for p in plan.phases
        ]

    def snapshot(self) -> tuple:
        snap = (
            tuple(_snap_step(s, self.quotient) for s in self.steps),
            tuple(_snap_strategy(s) for s in self.strategies),
        )
        if self.world is not None:
            return snap + (self.world.snapshot(),)
        return snap

    def restore(self, snap: tuple) -> None:
        step_snaps, strat_snaps = snap[0], snap[1]
        for step, ssnap in zip(self.steps, step_snaps):
            _restore_step(step, ssnap)
        for strategy, tsnap in zip(self.strategies, strat_snaps):
            _restore_strategy(strategy, tsnap)
        if self.world is not None:
            self.world.restore(snap[2])

    # -- events -------------------------------------------------------

    def events(self) -> List[Tuple[str, Callable[[], None]]]:
        """The full alphabet.  Enabledness is implicit: an event that
        does not change the snapshot is a self-loop and is dropped by
        the dedup, so "disabled" events cost one transition apply."""
        out: List[Tuple[str, Callable[[], None]]] = []
        overrides = getattr(self.world, "launch_overrides", {}) \
            if self.world is not None else {}
        for step in self.steps:
            name = step.name
            if isinstance(step, ActionStep):
                # scheduler-side step: its "launch" is execute(),
                # gated on candidacy exactly as run_cycle gates it.
                # No force_complete in the model alphabet: forcing a
                # kill/unreserve step asserts OUT-OF-BAND operator
                # knowledge (the processes are known dead, the claims
                # known released) the world cannot represent — modeled
                # instead by the world's own death/release events.
                out.append((f"execute({name})", self._executor(step)))
                out.append((f"restart({name})", step.restart))
                if self.step_interrupts:
                    out.append((f"interrupt({name})", step.interrupt))
                    out.append((f"proceed({name})", step.proceed))
                continue
            launcher = overrides.get(name) or self._launcher(step)
            out.append((f"launch({name})", launcher))
            # status events for EVERY task the step covers (a gang
            # step completes only when all its tasks report; a
            # single-task step is unchanged by the loop)
            for task, spec in step._spec_by_full.items():
                statuses = [
                    ("RUNNING", TaskState.RUNNING, False),
                    ("FINISHED", TaskState.FINISHED, False),
                    ("FAILED", TaskState.FAILED, False),
                    ("TASK_ERROR", TaskState.ERROR, False),
                ]
                if spec.readiness_check is not None:
                    # only meaningful with a readiness gate; elsewhere
                    # it just doubles RUNNING
                    statuses.insert(1, ("READY", TaskState.RUNNING, True))
                prefix = f"status({name}" if len(step._spec_by_full) == 1 \
                    else f"status({name}:{task}"
                for label, state, ready in statuses:
                    out.append((
                        f"{prefix},{label})",
                        self._status_sender(task, state, ready, _LIVE),
                    ))
                # a status from a launch that no longer exists
                # (reordered delivery across a restart) — must always
                # be ignored
                out.append((
                    f"{prefix},STALE_FAILED)",
                    self._status_sender(
                        task, TaskState.FAILED, False, _STALE
                    ),
                ))
            out.append((f"restart({name})", step.restart))
            out.append((f"force_complete({name})", step.force_complete))
            if self.step_interrupts:
                out.append((f"interrupt({name})", step.interrupt))
                out.append((f"proceed({name})", step.proceed))
        for i, phase in enumerate(self.plan.phases):
            out.append((f"interrupt(phase:{phase.name})", phase.interrupt))
            out.append((f"proceed(phase:{phase.name})", phase.proceed))
        out.append(("interrupt(plan)", self.plan.interrupt))
        out.append(("proceed(plan)", self.plan.proceed))
        if self.world is not None:
            out.extend(self.world.events(self))
        return out

    def _executor(self, step: ActionStep) -> Callable[[], None]:
        def execute() -> None:
            # run_cycle only executes CANDIDATES; the serial strategy
            # is what orders kill -> unreserve -> replace
            if step not in self.plan.candidates(set()):
                return
            step.execute(None)
        return execute

    def _launcher(self, step: DeploymentStep) -> Callable[[], None]:
        def launch() -> None:
            # the offer cycle only starts CANDIDATES: mutual exclusion
            # and ordering come from the strategies, exactly as in
            # PlanCoordinator.process_offers
            if step not in self.plan.candidates(set()):
                return
            requirement = step.start()
            if requirement is None:
                return
            step.record_launch({
                task: f"{task}__{_LIVE}"
                for task in requirement.task_names()
            })
        return launch

    def _status_sender(
        self, task: str, state: TaskState, ready: bool, suffix: str
    ) -> Callable[[], None]:
        # one immutable TaskStatus per event, built once: update()
        # never mutates the status, and the dataclass construction is
        # measurable at ~10^6 transitions
        status = TaskStatus(
            task_id=f"{task}__{suffix}",
            state=state,
            ready=ready,
            timestamp=1.0,
        )

        def send() -> None:
            self.plan.update(status)
        return send


def _quotient_probe(harness: PlanHarness) -> bool:
    """Verify the COMPLETE-residue quotient is sound for THESE step
    objects: craft representative COMPLETE states still carrying
    launch residue (expected ids, task states), fire every status and
    launch event at them, and require the step to stay COMPLETE with
    no errors.  A step class missing the is_complete guard (or a
    strategy that launches completed steps) fails the probe, the
    checker falls back to exhaustive snapshots, and the regression is
    REPORTED by the search instead of being quotiented away.

    Caller must restore the pre-probe snapshot afterwards.
    """
    events = harness.events()
    for step in harness.steps:
        if isinstance(step, ActionStep):
            continue  # no launch residue to quotient
        task = next(iter(step._spec_by_full))
        live = f"{task}__{_LIVE}"
        running = TaskState.RUNNING.value
        residues = [
            # natural completion: expected + RUNNING (+ready)
            (Status.COMPLETE.value, False, ((task, live),),
             ((task, running),), ((task, True),), False, ()),
            # force-complete mid-launch: expected, no states yet
            (Status.COMPLETE.value, False, ((task, live),),
             (), (), False, ()),
        ]
        mine = [
            ev for label, ev in events
            if label.startswith(f"status({step.name},")
            or label.startswith(f"status({step.name}:")
            or label == f"launch({step.name})"
        ]
        for residue in residues:
            for ev in mine:
                _restore_step(step, residue)
                ev()
                if step._status is not Status.COMPLETE or step.errors:
                    return False
    return True


# -- invariants -------------------------------------------------------------


@dataclass
class Violation:
    invariant: str
    detail: str
    trace: Tuple[str, ...]

    def render(self) -> str:
        steps = "\n".join(
            f"    {i + 1}. {event}" for i, event in enumerate(self.trace)
        ) or "    (initial state)"
        return (
            f"[{self.invariant}] {self.detail}\n"
            f"  minimal trace ({len(self.trace)} events):\n{steps}"
        )


class Invariant:
    """Base: override either hook.  ``on_transition`` sees the step
    statuses before/after one event; ``on_state`` sees each NEW
    deduplicated state once.  Return a violation detail string, or
    None."""

    name = ""

    def on_transition(
        self,
        harness: PlanHarness,
        before: Sequence[Status],
        event: str,
        after: Sequence[Status],
    ) -> Optional[str]:
        return None

    def on_state(self, harness: PlanHarness) -> Optional[str]:
        return None


def _restart_scope(event: str, step_name: str) -> bool:
    """True when ``event`` is a restart verb covering ``step_name``."""
    return event.startswith("restart(")


class NoSilentRegression(Invariant):
    """COMPLETE only leaves COMPLETE via an explicit restart."""

    name = "no-silent-regression"

    def on_transition(self, harness, before, event, after):
        for step, prev, cur in zip(harness.steps, before, after):
            if prev is Status.COMPLETE and cur is not Status.COMPLETE:
                if not _restart_scope(event, step.name):
                    return (
                        f"step {step.name} regressed COMPLETE -> "
                        f"{cur.value} on {event} (only restart may do "
                        "that)"
                    )
        return None


class ErrorAbsorbs(Invariant):
    """ERROR is sticky until an operator restart/force-complete."""

    name = "error-absorbs"

    def on_transition(self, harness, before, event, after):
        for step, prev, cur in zip(harness.steps, before, after):
            if prev is Status.ERROR and cur is not Status.ERROR:
                if not (
                    event.startswith("restart(")
                    or event.startswith("force_complete(")
                ):
                    return (
                        f"step {step.name} left ERROR -> {cur.value} on "
                        f"{event} without operator intervention"
                    )
        return None


class AggregateConsistent(Invariant):
    """aggregate() is order-insensitive on every reached multiset,
    ERROR dominates, all-COMPLETE <=> COMPLETE (non-empty)."""

    name = "aggregate-consistent"

    def __init__(self) -> None:
        self._checked: set = set()

    def on_state(self, harness):
        groups: List[Tuple[Status, ...]] = [
            tuple(s.get_status() for s in phase.steps)
            for phase in harness.plan.phases
        ]
        groups.append(tuple(
            p.get_status() for p in harness.plan.phases
        ))
        for statuses in groups:
            for interrupted in (False, True):
                key = (tuple(sorted(s.value for s in statuses)),
                       interrupted)
                if key in self._checked:
                    continue
                self._checked.add(key)
                detail = self._check_multiset(statuses, interrupted)
                if detail:
                    return detail
        return None

    @staticmethod
    def _check_multiset(statuses, interrupted):
        base = aggregate(statuses, interrupted)
        seq = list(statuses)
        perms = (
            itertools.permutations(seq) if len(seq) <= 4
            else [seq, list(reversed(seq)),
                  sorted(seq, key=lambda s: s.value)]
        )
        for perm in perms:
            got = aggregate(perm, interrupted)
            if got is not base:
                return (
                    f"aggregate({[s.value for s in seq]}, "
                    f"interrupted={interrupted}) is order-sensitive: "
                    f"{base.value} vs {got.value} for "
                    f"{[s.value for s in perm]}"
                )
        if statuses:
            all_complete = all(s is Status.COMPLETE for s in statuses)
            if all_complete and base is not Status.COMPLETE:
                return (
                    f"aggregate of all-COMPLETE reads {base.value}"
                )
            if not all_complete and base is Status.COMPLETE:
                return (
                    f"aggregate({[s.value for s in statuses]}) reads "
                    "COMPLETE with incomplete children"
                )
            if any(s is Status.ERROR for s in statuses) and \
                    base is not Status.ERROR:
                return (
                    f"aggregate({[s.value for s in statuses]}) hides a "
                    f"child ERROR behind {base.value}"
                )
        return None


class DependencyHonored(Invariant):
    """DependencyStrategy never emits a candidate whose declared
    dependency is non-COMPLETE."""

    name = "dependency-honored"

    def on_state(self, harness):
        for phase in harness.plan.phases:
            strategy = phase.strategy
            if not isinstance(strategy, DependencyStrategy):
                continue
            by_name = {s.name: s for s in phase.steps}
            for cand in phase.candidates(set()):
                for dep in strategy._edges.get(cand.name, ()):
                    dep_step = by_name.get(dep)
                    if dep_step is not None and not dep_step.is_complete:
                        return (
                            f"{cand.name} emitted as candidate while "
                            f"dependency {dep} is "
                            f"{dep_step.get_status().value}"
                        )
        return None


class InterruptVisible(Invariant):
    """An incomplete WAITING child surfaces at the parent: the
    operator who parked a step must see WAITING in `plan show`, not a
    parent claiming PENDING/IN_PROGRESS while nothing can move."""

    name = "interrupt-visible"

    def on_state(self, harness):
        for phase in harness.plan.phases:
            statuses = [s.get_status() for s in phase.steps]
            parent = phase.get_status()
            if (
                Status.WAITING in statuses
                and parent in (Status.PENDING, Status.IN_PROGRESS)
                and not any(s.is_running for s in statuses)
                and not any(
                    s in (Status.PENDING, Status.DELAYED)
                    for s in statuses
                )
            ):
                return (
                    f"phase {phase.name} reads {parent.value} but its "
                    "only incomplete children are WAITING (interrupt "
                    "hidden from the operator)"
                )
        return None


def default_invariants() -> List[Invariant]:
    return [
        NoSilentRegression(),
        ErrorAbsorbs(),
        AggregateConsistent(),
        DependencyHonored(),
        InterruptVisible(),
    ]


# -- the checker ------------------------------------------------------------


@dataclass
class CheckResult:
    config: str
    states: int
    transitions: int
    complete_states: int
    truncated: bool
    violations: List[Violation] = field(default_factory=list)
    livelock_checked: bool = False
    # False = the probe found a step reacting to post-COMPLETE events
    # and the run fell back to exhaustive (un-quotiented) snapshots
    quotient: bool = True

    @property
    def ok(self) -> bool:
        return not self.violations


def check_plan(
    factory: Callable[[], Plan],
    invariants: Optional[Iterable[Invariant]] = None,
    max_states: int = 200_000,
    max_violations: int = 5,
    config_name: str = "plan",
    check_livelock: bool = True,
    step_interrupts: bool = False,
) -> CheckResult:
    """Exhaustively explore ``factory()``'s plan under the full event
    alphabet; returns states explored, violations with minimal traces.

    The factory is called once — exploration runs on the live object
    graph via snapshot/restore, so the checker checks the REAL
    production classes, not a transcription of them.
    """
    made = factory()
    if isinstance(made, tuple):
        plan, world = made
    else:
        plan, world = made, None
    harness = PlanHarness(
        plan, step_interrupts=step_interrupts, world=world
    )
    invs = list(invariants) if invariants is not None \
        else default_invariants()
    if world is not None and hasattr(world, "invariants"):
        invs += world.invariants()
    events = harness.events()

    pre_probe = harness.snapshot()
    harness.quotient = _quotient_probe(harness)
    harness.restore(pre_probe)
    init = harness.snapshot()
    # state -> (parent state, event label) for minimal-trace replay
    parents: Dict[tuple, Optional[Tuple[tuple, str]]] = {init: None}
    order: List[tuple] = [init]
    edges: List[Tuple[int, int]] = []
    index: Dict[tuple, int] = {init: 0}
    complete: List[int] = []
    violations: List[Violation] = []
    transitions = 0
    truncated = False

    def trace_of(state: tuple, extra: Optional[str] = None) -> Tuple[str, ...]:
        out: List[str] = []
        cur = state
        while parents[cur] is not None:
            prev, label = parents[cur]
            out.append(label)
            cur = prev
        out.reverse()
        if extra:
            out.append(extra)
        return tuple(out)

    def statuses_of() -> Tuple[Status, ...]:
        return tuple(s.get_status() for s in harness.steps)

    head = 0
    while head < len(order):
        state = order[head]
        head += 1
        harness.restore(state)
        before = statuses_of()
        if harness.plan.get_status() is Status.COMPLETE:
            complete.append(index[state])
        for label, apply_event in events:
            harness.restore(state)
            apply_event()
            after = harness.snapshot()
            transitions += 1
            if after == state:
                continue  # self-loop: disabled or no-op event
            after_statuses = statuses_of()
            for inv in invs:
                detail = inv.on_transition(
                    harness, before, label, after_statuses
                )
                if detail and len(violations) < max_violations:
                    violations.append(Violation(
                        inv.name, detail, trace_of(state, label)
                    ))
            if after in parents:
                edges.append((index[state], index[after]))
                continue
            parents[after] = (state, label)
            index[after] = len(order)
            edges.append((index[state], index[after]))
            order.append(after)
            for inv in invs:
                detail = inv.on_state(harness)
                if detail and len(violations) < max_violations:
                    violations.append(Violation(
                        inv.name, detail, trace_of(after)
                    ))
            if len(order) >= max_states:
                truncated = True
                break
        if truncated:
            break

    result = CheckResult(
        config=config_name,
        states=len(order),
        transitions=transitions,
        complete_states=len(complete),
        truncated=truncated,
        violations=violations,
        quotient=harness.quotient,
    )

    # livelock: backward reachability from every plan-COMPLETE state.
    # Only sound on the full graph — a truncated frontier could hold
    # the missing escape path.
    if check_livelock and not truncated:
        result.livelock_checked = True
        reach_complete = set(complete)
        reverse: Dict[int, List[int]] = {}
        for src, dst in edges:
            reverse.setdefault(dst, []).append(src)
        frontier = list(reach_complete)
        while frontier:
            node = frontier.pop()
            for src in reverse.get(node, ()):
                if src not in reach_complete:
                    reach_complete.add(src)
                    frontier.append(src)
        if len(reach_complete) < len(order) and \
                len(violations) < max_violations:
            trapped = min(
                i for i in range(len(order)) if i not in reach_complete
            )
            violations.append(Violation(
                "no-livelock",
                f"{len(order) - len(reach_complete)} reachable state(s) "
                "can never reach plan COMPLETE; first trapped state "
                "shown",
                trace_of(order[trapped]),
            ))
    return result


# -- built-in configurations ------------------------------------------------


def _pod(name: str, readiness: bool = False,
         goal: GoalState = GoalState.RUNNING) -> PodSpec:
    from dcos_commons_tpu.specification.specs import ReadinessCheckSpec

    return PodSpec(
        type=name,
        count=1,
        tasks=[TaskSpec(
            name="server", goal=goal, cmd="run",
            readiness_check=(
                ReadinessCheckSpec(cmd="check") if readiness else None
            ),
        )],
    )


def _step(name: str, pod_type: str, readiness: bool = False,
          goal: GoalState = GoalState.RUNNING) -> DeploymentStep:
    return DeploymentStep(
        name,
        PodInstanceRequirement(pod=_pod(pod_type, readiness, goal),
                               instances=[0]),
        backoff=ModelBackoff(),
    )


def _serial_plan() -> Plan:
    phase1 = Phase(
        "node", [_step("node-0", "na"), _step("node-1", "nb")],
        SerialStrategy(),
    )
    phase2 = Phase("sidecar", [_step("sidecar-0", "sc")], SerialStrategy())
    return Plan("deploy", [phase1, phase2], SerialStrategy())


def _parallel_plan() -> Plan:
    # readiness-gated task + a FINISH-goal sidecar: exercises the
    # STARTED -> COMPLETE readiness edge and the FINISHED mapping
    phase = Phase(
        "node",
        [_step("node-0", "pa", readiness=True),
         _step("node-1", "pb", goal=GoalState.FINISH)],
        ParallelStrategy(),
    )
    return Plan("deploy", [phase], SerialStrategy())


def _dependency_plan() -> Plan:
    phase = Phase(
        "pipeline",
        [_step("stage-a", "da"), _step("stage-b", "db"),
         _step("stage-c", "dc")],
        DependencyStrategy({"stage-b": ["stage-a"],
                            "stage-c": ["stage-a"]}),
    )
    return Plan("deploy", [phase], SerialStrategy())


def _canary_plan() -> Plan:
    phase = Phase(
        "node", [_step("canary-0", "ca"), _step("canary-1", "cb")],
        CanaryStrategy(SerialStrategy(), canary_count=1),
    )
    return Plan("update", [phase], SerialStrategy())


# -- the gang-recovery configuration (ISSUE 13) -----------------------
#
# Models DefaultRecoveryPlanManager._make_gang_phase's choreography
# with the REAL plan objects (ActionStep kill/unreserve + a gang
# DeploymentStep replace under SerialStrategy) over a tiny world of
# the non-plan facts the steps mutate: which OLD incarnation
# processes still run, and which incarnation holds reservations.
# Old-task deaths arrive as world events (covering kill acks AND a
# second preemption landing mid-recovery — the storm case); the
# replace launch carries the WAL side effect (reservations commit
# with the launch).  Verified invariants:
#
#   no-split-brain-gang      an old-incarnation process never
#                            coexists with a RUNNING new-incarnation
#                            task (the wedged-collective guarantee)
#   no-double-reservation    the broken sub-slice's claims are
#                            released before the replacement gang's
#                            claims commit


class GangRecoveryWorld:
    """Non-plan model state for the gang-recovery configuration."""

    # surviving old-incarnation processes at entry; each dies
    # independently at any point (kill ack or mid-recovery
    # preemption), so the subset lattice is explored exhaustively.
    # 4 old x 2-host replacement gang lands the configuration at
    # ~10k states in ~12s — deep enough for the storm interleavings,
    # cheap enough for the repo gate.
    N_OLD = 4

    def __init__(self, kill_step, unreserve_step, replace_step):
        self.kill_step = kill_step
        self.unreserve_step = unreserve_step
        self.replace_step = replace_step
        self.old_alive = frozenset(range(self.N_OLD))
        self.old_reserved = True
        self.new_reserved = False
        self.launch_overrides = {
            replace_step.name: self._launch_replace,
        }
        self._plan: Optional[Plan] = None

    def bind(self, plan: Plan) -> "GangRecoveryWorld":
        self._plan = plan
        return self

    # -- snapshot protocol -------------------------------------------

    def snapshot(self) -> tuple:
        return (self.old_alive, self.old_reserved, self.new_reserved)

    def restore(self, snap: tuple) -> None:
        self.old_alive, self.old_reserved, self.new_reserved = snap

    # -- model events -------------------------------------------------

    def events(self, harness: "PlanHarness"):
        out = []
        for i in range(self.N_OLD):
            # an old process dies: a kill ack, OR the host it sat on
            # getting preempted mid-recovery (the storm case) — the
            # model does not distinguish, the plan must tolerate both
            # at ANY point
            out.append((
                f"old-task-dies({i})",
                lambda i=i: self._die(i),
            ))
        return out

    def _die(self, i: int) -> None:
        self.old_alive = self.old_alive - {i}

    def _launch_replace(self) -> None:
        step = self.replace_step
        if step not in self._plan.candidates(set()):
            return
        requirement = step.start()
        if requirement is None:
            return
        # WAL discipline: reservations are durable WITH the launch
        # record (run_cycle commits the ledger inside the launch span)
        self.new_reserved = True
        step.record_launch({
            task: f"{task}__{_LIVE}"
            for task in requirement.task_names()
        })

    # -- model actions (close over self; ActionStep passes None) ------

    def kill_survivors(self, _scheduler) -> bool:
        # issues kills; completes only when nothing old is alive —
        # exactly DefaultRecoveryPlanManager's kill action, with the
        # agent's process table abstracted to ``old_alive``
        return not self.old_alive

    def unreserve_slice(self, _scheduler) -> bool:
        self.old_reserved = False
        return True

    # -- invariants ----------------------------------------------------

    def invariants(self) -> List["Invariant"]:
        return [NoSplitBrainGang(), NoDoubleReservation()]


class NoSplitBrainGang(Invariant):
    """Old and new gang incarnations never run simultaneously: a new
    task reaching RUNNING while an old process survives means two
    incarnations fight over the checkpoint directory and the
    collective fabric (incarnation fencing makes the loser's WRITES
    harmless, but the plan must never create the overlap)."""

    name = "no-split-brain-gang"

    def on_state(self, harness):
        world = harness.world
        if not world.old_alive:
            return None
        step = world.replace_step
        # the hazard is a RUNNING new task while an old process lives.
        # A force-completed replace step with no launch is NOT a
        # split brain — the operator skipped the relaunch, nothing
        # new runs (and any status-driven COMPLETE passed through a
        # RUNNING state this check already saw).
        running = [
            task for task, state in step._task_states.items()
            if state is TaskState.RUNNING
        ]
        if running:
            return (
                f"old incarnation processes {sorted(world.old_alive)} "
                f"still alive while replacement gang runs "
                f"{sorted(running)}"
            )
        return None


class NoDoubleReservation(Invariant):
    """The broken sub-slice's reservations are released before the
    replacement gang's commit: overlapping claims would double-count
    capacity and can double-book chips once the freed hosts re-enter
    the candidate set mid-evaluation."""

    name = "no-double-reservation"

    def on_state(self, harness):
        world = harness.world
        if world.old_reserved and world.new_reserved:
            return (
                "broken sub-slice still reserved while the replacement "
                "gang holds committed reservations"
            )
        return None


def _gang_recovery_plan():
    from dcos_commons_tpu.plan.strategy import SerialStrategy as _Serial

    # the REPLACEMENT gang is 2 hosts (an elastic shrink of the 4 old
    # survivors' slice) — decoupled from N_OLD on purpose: the
    # replace step's task lattice and the old-process subset lattice
    # multiply, and 2x4 is the sweet spot between depth and gate cost
    pod = PodSpec(
        type="trainer",
        count=2,
        gang=True,
        tasks=[TaskSpec(name="worker", goal=GoalState.RUNNING,
                        cmd="train")],
    )
    replace = DeploymentStep(
        "replace-trainer-gang",
        PodInstanceRequirement(
            pod=pod,
            instances=list(range(pod.count)),
        ),
        backoff=ModelBackoff(),
    )
    # world first (the action callables close over it), steps after
    kill = ActionStep("kill-trainer-survivors", lambda s: False)
    unreserve = ActionStep("unreserve-trainer-slice", lambda s: False)
    world = GangRecoveryWorld(kill, unreserve, replace)
    kill._action = world.kill_survivors
    unreserve._action = world.unreserve_slice
    phase = Phase(
        "recover-trainer-gang", [kill, unreserve, replace], _Serial()
    )
    plan = Plan("recovery", [phase], _Serial())
    world.bind(plan)
    return plan, world


# -- the multi-slice recovery configuration (ISSUE 20) ----------------
#
# Models the whole-slice elastic choreography end to end: a
# dcn-spanning gang loses one slice, the recovery plan shrinks to the
# surviving slice (kill-survivors -> unreserve-dead-slice ->
# replace-shrunken), and when slice capacity returns the manager's
# regrow phase widens back to declared width (kill-shrunken ->
# unreserve-shrunken -> replace-full).  THREE incarnations (old /
# shrunken / full) share one fabric and one ledger, so the
# gang-recovery invariants quantify over all of them:
#
#   no-split-brain-multislice   no older incarnation's process is
#                               alive while a newer incarnation's
#                               task runs (shrink AND regrow edges)
#   no-double-slice-reservation two incarnations never hold committed
#                               claims simultaneously
#
# The production manager synthesizes the regrow phase only once a
# fresh slice registers; the model pre-builds both phases and gates
# the regrow's first action (and the full-width launch) on the
# ``slice-capacity-returns`` world event instead.  The
# ``regrow_skips_kill`` knob exists ONLY for the seeded-bug fixture
# in test_lint_gate: a regrow that relaunches the declared width
# without first killing + unreserving the shrunken gang is caught by
# both invariants with a minimal trace.


class MultiSliceRecoveryWorld:
    """Non-plan model state for the multislice-recovery config."""

    # survivors of the OLD full-width incarnation on the live slice,
    # and the shrunken replacement's width.  2 x 2 x six steps across
    # two serial phases x the capacity bit lands ~40k states — well
    # past the 10k repo-gate bar, untruncated under its 120k cap.
    # Per-step interrupt verbs are OFF for this configuration (the
    # phase/plan interrupts stay in the alphabet): six steps double
    # six times and the space blows through the cap.
    N_OLD = 2
    N_SHRUNK = 2

    def __init__(self, replace_shrunk, replace_full):
        self.replace_shrunk = replace_shrunk
        self.replace_full = replace_full
        self.old_alive = frozenset(range(self.N_OLD))
        self.shrunk_alive: frozenset = frozenset()
        self.old_reserved = True
        self.shrunk_reserved = False
        self.full_reserved = False
        self.capacity = False
        # set once the regrow choreography arms: the production
        # manager REPLACES the shrink phase with the regrow phase in
        # its phase map, so the shrink replace step cannot relaunch
        # afterwards — the model keeps both phases alive and fences
        # the stale launch path with this bit instead
        self.regrow_begun = False
        self.launch_overrides = {
            replace_shrunk.name: self._launch_shrunk,
            replace_full.name: self._launch_full,
        }
        self._plan: Optional[Plan] = None

    def bind(self, plan: Plan) -> "MultiSliceRecoveryWorld":
        self._plan = plan
        return self

    # -- snapshot protocol -------------------------------------------

    def snapshot(self) -> tuple:
        return (
            self.old_alive, self.shrunk_alive, self.old_reserved,
            self.shrunk_reserved, self.full_reserved, self.capacity,
            self.regrow_begun,
        )

    def restore(self, snap: tuple) -> None:
        (self.old_alive, self.shrunk_alive, self.old_reserved,
         self.shrunk_reserved, self.full_reserved, self.capacity,
         self.regrow_begun) = snap

    # -- model events -------------------------------------------------

    def events(self, harness: "PlanHarness"):
        out = []
        for i in range(self.N_OLD):
            # a surviving old worker dies at ANY point: kill ack or a
            # second preemption landing mid-recovery (the storm case)
            out.append((
                f"old-task-dies({i})",
                lambda i=i: self._die_old(i),
            ))
        for i in range(self.N_SHRUNK):
            # a shrunken worker dies mid-regrow — the kill step's ack,
            # or the surviving slice getting reclaimed too
            out.append((
                f"shrunk-task-dies({i})",
                lambda i=i: self._die_shrunk(i),
            ))
        out.append((
            "slice-capacity-returns", self._capacity_returns,
        ))
        return out

    def _die_old(self, i: int) -> None:
        self.old_alive = self.old_alive - {i}

    def _die_shrunk(self, i: int) -> None:
        self.shrunk_alive = self.shrunk_alive - {i}

    def _capacity_returns(self) -> None:
        self.capacity = True

    def _launch_shrunk(self) -> None:
        step = self.replace_shrunk
        if step not in self._plan.candidates(set()):
            return
        # placement feasibility the model MUST keep: the shrunken
        # gang targets the surviving slice, and an offer cycle
        # declines while another incarnation's claims sit on those
        # chips — without this, an operator restart of the completed
        # shrink step after regrow would "place" over the full gang
        if self.old_reserved or self.full_reserved:
            return
        if self.regrow_begun:
            return  # the manager swapped phases: this step is stale
        requirement = step.start()
        if requirement is None:
            return
        self.shrunk_reserved = True
        # a (re)launch is a fresh set of shrunken processes
        self.shrunk_alive = frozenset(range(self.N_SHRUNK))
        step.record_launch({
            task: f"{task}__{_LIVE}"
            for task in requirement.task_names()
        })

    def _launch_full(self) -> None:
        step = self.replace_full
        if step not in self._plan.candidates(set()):
            return
        if not self.capacity:
            return  # no fresh slice registered: the offer declines
        # deliberately NO claim-feasibility guard here (mirroring
        # GangRecoveryWorld's launch): the invariant certifies the
        # PLAN orders unreserve-shrunken before replace-full — the
        # choreography must not lean on evaluator feasibility to
        # avoid the double-commit
        requirement = step.start()
        if requirement is None:
            return
        self.full_reserved = True
        step.record_launch({
            task: f"{task}__{_LIVE}"
            for task in requirement.task_names()
        })

    # -- model actions (close over self; ActionStep passes None) ------

    def kill_old_survivors(self, _scheduler) -> bool:
        return not self.old_alive

    def unreserve_dead_slice(self, _scheduler) -> bool:
        self.old_reserved = False
        return True

    def kill_shrunken_gang(self, _scheduler) -> bool:
        # the regrow choreography arms only once a fresh slice
        # registers (the manager's capacity probe), then completes
        # when nothing shrunken is left running
        if not self.capacity:
            return False
        self.regrow_begun = True
        return not self.shrunk_alive

    def unreserve_shrunken_claims(self, _scheduler) -> bool:
        self.shrunk_reserved = False
        return True

    # -- invariants ----------------------------------------------------

    def invariants(self) -> List["Invariant"]:
        return [NoSplitBrainMultiSlice(), NoDoubleSliceReservation()]


class NoSplitBrainMultiSlice(Invariant):
    """No older incarnation's process survives while a newer
    incarnation's task runs: old-vs-shrunken is the wedged-collective
    guarantee from the single-slice configuration, and
    shrunken-vs-full is the regrow edge — the widened gang re-forms
    the dcn ring over the surviving slice's chips, so a leftover
    shrunken worker there fights the full gang for its own fabric."""

    name = "no-split-brain-multislice"

    def on_state(self, harness):
        world = harness.world
        hazards = (
            (world.old_alive, world.replace_shrunk, "old"),
            (world.old_alive, world.replace_full, "old"),
            (world.shrunk_alive, world.replace_full, "shrunken"),
        )
        for ghosts, step, label in hazards:
            if not ghosts:
                continue
            running = [
                task for task, state in step._task_states.items()
                if state is TaskState.RUNNING
            ]
            if running:
                return (
                    f"{label} incarnation processes {sorted(ghosts)} "
                    f"still alive while {step.name} runs "
                    f"{sorted(running)}"
                )
        return None


class NoDoubleSliceReservation(Invariant):
    """At most one gang incarnation holds committed claims: the
    shrink must release the dead span's rows before the shrunken
    commit, and the regrow must release the shrunken rows before the
    full-width commit — overlap double-counts the surviving slice's
    chips in the ledger."""

    name = "no-double-slice-reservation"

    def on_state(self, harness):
        world = harness.world
        holders = [
            label for label, held in (
                ("old", world.old_reserved),
                ("shrunken", world.shrunk_reserved),
                ("full", world.full_reserved),
            ) if held
        ]
        if len(holders) > 1:
            return (
                f"incarnations {holders} hold committed reservations "
                "simultaneously"
            )
        return None


def _multislice_recovery_plan(regrow_skips_kill: bool = False):
    from dcos_commons_tpu.plan.strategy import SerialStrategy as _Serial

    # the gang declares 2 slices x 2 hosts; the shrunken incarnation
    # is the surviving slice's pair (instances 0/1) and the regrown
    # full width relaunches EVERYTHING — the model tracks ONE
    # fresh-slice worker (instance 2) for the full step so the two
    # incarnations' status alphabets stay disjoint (production reuses
    # names with fresh task ids; the model's __live suffix cannot
    # carry that distinction) and the state space clears the repo
    # gate untruncated; the hazards quantify over ANY running full
    # task, so one representative is enough
    shrunk_pod = PodSpec(
        type="trainer",
        count=2,
        gang=True,
        tasks=[TaskSpec(name="worker", goal=GoalState.RUNNING,
                        cmd="train")],
    )
    full_pod = PodSpec(
        type="trainer",
        count=4,
        gang=True,
        tasks=[TaskSpec(name="worker", goal=GoalState.RUNNING,
                        cmd="train")],
    )
    replace_shrunk = DeploymentStep(
        "replace-shrunken-gang",
        PodInstanceRequirement(pod=shrunk_pod, instances=[0, 1]),
        backoff=ModelBackoff(),
    )
    replace_full = DeploymentStep(
        "replace-full-gang",
        PodInstanceRequirement(pod=full_pod, instances=[2]),
        backoff=ModelBackoff(),
    )
    kill_old = ActionStep("kill-old-survivors", lambda s: False)
    unreserve_old = ActionStep("unreserve-dead-slice", lambda s: False)
    kill_shrunk = ActionStep("kill-shrunken-gang", lambda s: False)
    unreserve_shrunk = ActionStep(
        "unreserve-shrunken-gang", lambda s: False
    )
    world = MultiSliceRecoveryWorld(replace_shrunk, replace_full)
    kill_old._action = world.kill_old_survivors
    unreserve_old._action = world.unreserve_dead_slice
    kill_shrunk._action = world.kill_shrunken_gang
    unreserve_shrunk._action = world.unreserve_shrunken_claims
    shrink = Phase(
        "shrink-to-surviving-slice",
        [kill_old, unreserve_old, replace_shrunk],
        _Serial(),
    )
    regrow_steps = [replace_full] if regrow_skips_kill else [
        kill_shrunk, unreserve_shrunk, replace_full,
    ]
    regrow = Phase(
        "regrow-to-declared-width", regrow_steps, _Serial()
    )
    plan = Plan("recovery", [shrink, regrow], _Serial())
    world.bind(plan)
    return plan, world


def _multislice_recovery_plan_strict():
    return _multislice_recovery_plan()


# -- the autoscale configuration (ISSUE 15) ---------------------------
#
# Models the closed health->action loop's no-flap algebra with the
# REAL plan objects AND the REAL decision functions
# (health/actions.py decide() / remediation_allowed() — not a
# transcription): three scale actions (two successive scale-outs and
# a scale-in, so the cooldown latch between same-direction actions is
# reachable) plus a remediation flag, driven by breach/cooldown
# toggles, a deterministic governor tick, and a settle event that
# starts cooldown clocks at every terminal action state.  Arming is
# gated by the WORLD (action callables return False and the launch
# override no-ops while unarmed), so the whole operator-verb alphabet
# stays live without a per-phase interrupt blow-up.  Verified
# invariants (the issue's no-flap contract):
#
#   no-opposite-concurrent   a scale-out and a scale-in are never
#                            armed simultaneously (single flight)
#   cooldown-honored         no same-direction action arms while its
#                            direction's cooldown latch is set
#   no-remediation-storm     remediation never arms while any scale
#                            action is armed (at most one eviction
#                            per service at a time)
#
# The ``honor_cooldown`` / ``single_flight`` knobs exist ONLY for the
# seeded-flap fixture in test_lint_gate: a governor that skips either
# check is caught with a minimal trace.

_NOW = 1_000.0


class AutoscaleWorld:
    """Non-plan model state for the autoscale configuration."""

    DIRECTION = {
        "scale-out-a": "out",
        "scale-out-b": "out",
        "scale-in-a": "in",
    }

    def __init__(self, actions: Dict[str, List],
                 honor_cooldown: bool = True,
                 single_flight: bool = True):
        from dcos_commons_tpu.health.actions import ActionPolicy

        # action name -> its steps (all must complete to settle)
        self.actions = actions
        self.honor_cooldown = honor_cooldown
        self.single_flight = single_flight
        self.policy = ActionPolicy(
            autoscale=True, breach_hold_s=0.0, quiet_hold_s=0.0,
            max_instances=4, cooldown_out_s=60.0, cooldown_in_s=60.0,
        )
        self.breach = False
        self.quiet = False
        self.cool_out = False
        self.cool_in = False
        self.replace_active = False
        self.armed: frozenset = frozenset()
        # set (only reachable with broken knobs) when remediation
        # armed while a scale action was armed — the storm marker
        self.storm = False
        self.launch_overrides = {}
        for name, steps in actions.items():
            for step in steps:
                if isinstance(step, ActionStep):
                    step._action = self._gated_action(name)
                else:
                    self.launch_overrides[step.name] = \
                        self._gated_launch(name, step)
        self._plan: Optional[Plan] = None

    def bind(self, plan: Plan) -> "AutoscaleWorld":
        self._plan = plan
        return self

    # -- snapshot protocol -------------------------------------------

    def snapshot(self) -> tuple:
        return (self.breach, self.quiet, self.cool_out, self.cool_in,
                self.replace_active, self.armed, self.storm)

    def restore(self, snap: tuple) -> None:
        (self.breach, self.quiet, self.cool_out, self.cool_in,
         self.replace_active, self.armed, self.storm) = snap

    # -- arming gates --------------------------------------------------

    def _gated_action(self, name: str):
        def action(_scheduler) -> bool:
            # the engine only has steps for ARMED actions; here the
            # phase is pre-built, so unarmed steps simply make no
            # progress (stay PENDING)
            return name in self.armed
        return action

    def _gated_launch(self, name: str, step: DeploymentStep):
        def launch() -> None:
            if name not in self.armed:
                return
            if step not in self._plan.candidates(set()):
                return
            requirement = step.start()
            if requirement is None:
                return
            step.record_launch({
                task: f"{task}__{_LIVE}"
                for task in requirement.task_names()
            })
        return launch

    def _steps_complete(self, name: str) -> bool:
        return all(s.get_status().is_complete for s in self.actions[name])

    # -- model events -------------------------------------------------

    def events(self, harness: "PlanHarness"):
        return [
            ("breach-start", lambda: self._set_breach(True)),
            ("breach-end", lambda: self._set_breach(False)),
            ("quiet-start", lambda: self._set_quiet(True)),
            ("quiet-end", lambda: self._set_quiet(False)),
            ("cooldown-out-expires", lambda: self._expire("out")),
            ("cooldown-in-expires", lambda: self._expire("in")),
            ("governor-tick", self._tick),
            ("settle", self._settle),
            ("replace-done", self._replace_done),
        ]

    def _set_breach(self, value: bool) -> None:
        self.breach = value

    def _set_quiet(self, value: bool) -> None:
        self.quiet = value

    def _expire(self, direction: str) -> None:
        if direction == "out":
            self.cool_out = False
        else:
            self.cool_in = False

    def _replace_done(self) -> None:
        self.replace_active = False

    def _tick(self) -> None:
        """One governor pass: applies the REAL decide() /
        remediation_allowed() (with the knob-degraded inputs a broken
        governor would pass) and arms at most one action."""
        from dcos_commons_tpu.health.actions import (
            decide,
            remediation_allowed,
        )

        active_dirs = {self.DIRECTION[n] for n in self.armed}
        active = (
            sorted(active_dirs)[0]
            if active_dirs and self.single_flight else None
        )
        far = _FAR_FUTURE
        for name in ("scale-out-a", "scale-out-b", "scale-in-a"):
            if name in self.armed or self._steps_complete(name):
                continue
            decision = decide(
                _NOW,
                policy=self.policy,
                count=2,
                baseline=1,
                breach_since=0.0 if self.breach else None,
                severity=4.0,
                quiet_since=0.0 if self.quiet else None,
                active=active,
                hold=False,
                cooldown_out_until=(
                    far if (self.cool_out and self.honor_cooldown)
                    else 0.0
                ),
                cooldown_in_until=(
                    far if (self.cool_in and self.honor_cooldown)
                    else 0.0
                ),
            )
            if decision is not None and \
                    decision.direction == self.DIRECTION[name]:
                self.armed = self.armed | {name}
                return
        if not self.replace_active and remediation_allowed(
            _NOW,
            enabled=True,
            scale_active=bool(self.armed) and self.single_flight,
            hold=False,
            last_replace_t=None,
            cooldown_s=0.0,
        ):
            if self.armed:
                self.storm = True  # only reachable with broken knobs
            self.replace_active = True

    def _settle(self) -> None:
        """Terminal action states start their direction's cooldown
        clock and disarm — the engine's _settle, every terminal state
        counted (natural completion and operator force-complete
        alike)."""
        for name in sorted(self.armed):
            if not self._steps_complete(name):
                continue
            self.armed = self.armed - {name}
            if self.DIRECTION[name] == "out":
                self.cool_out = True
            else:
                self.cool_in = True

    # -- invariants ----------------------------------------------------

    def invariants(self) -> List["Invariant"]:
        return [NoOppositeConcurrent(), CooldownHonored(),
                NoRemediationStorm()]


class NoOppositeConcurrent(Invariant):
    """A scale-out and a scale-in never run concurrently: the pair
    would thrash capacity (the scale-in killing what the scale-out
    just deployed) — the definition of flapping."""

    name = "no-opposite-concurrent"

    def on_state(self, harness):
        world = harness.world
        dirs = {world.DIRECTION[n] for n in world.armed}
        if "out" in dirs and "in" in dirs:
            return (
                f"opposite-direction actions armed concurrently: "
                f"{sorted(world.armed)}"
            )
        return None


class CooldownHonored(Invariant):
    """No same-direction action is armed while that direction's
    cooldown latch (set at every terminal action state) is still
    set: settle disarms atomically with latching, so the overlap is
    reachable only through a governor that skipped the cooldown
    check."""

    name = "cooldown-honored"

    def on_state(self, harness):
        world = harness.world
        for name in sorted(world.armed):
            direction = world.DIRECTION[name]
            cooling = (
                world.cool_out if direction == "out" else world.cool_in
            )
            if cooling:
                return (
                    f"{name} armed while the {direction}-direction "
                    "cooldown is still latched"
                )
        return None


class NoRemediationStorm(Invariant):
    """Remediation never arms while a scale action is armed: an
    automated eviction racing an automated resize is the storm the
    single-flight rule exists to prevent (at most one automated
    eviction per service at a time)."""

    name = "no-remediation-storm"

    def on_state(self, harness):
        if harness.world.storm:
            return (
                "remediation armed while a scale action was in "
                f"flight ({sorted(harness.world.armed)})"
            )
        return None


def _autoscale_plan(honor_cooldown: bool = True,
                    single_flight: bool = True):
    grow_a = ActionStep("grow-serve-to-3", lambda s: False)
    deploy_a = DeploymentStep(
        "deploy-serve-2",
        PodInstanceRequirement(pod=_pod("serve", readiness=True),
                               instances=[2]),
        backoff=ModelBackoff(),
    )
    grow_b = ActionStep("grow-serve-to-4", lambda s: False)
    shrink = ActionStep("shrink-serve-to-1", lambda s: False)
    world = AutoscaleWorld(
        {
            "scale-out-a": [grow_a, deploy_a],
            "scale-out-b": [grow_b],
            "scale-in-a": [shrink],
        },
        honor_cooldown=honor_cooldown,
        single_flight=single_flight,
    )
    phase = Phase(
        "autoscale-serve", [grow_a, deploy_a, grow_b, shrink],
        ParallelStrategy(),
    )
    plan = Plan("autoscale", [phase], ParallelStrategy())
    world.bind(plan)
    return plan, world


def _autoscale_plan_strict():
    return _autoscale_plan()


# -- the migration configuration (ISSUE 16) ---------------------------
#
# Models serve/migration.py's fenced cutover protocol (freeze ->
# stream/splice -> cutover -> release) as four ActionSteps under
# SerialStrategy over a world of the two pods' session facts: where
# the session's row is live (source serving / frozen / released,
# destination none / spliced / active), with operator abort and
# either pod dying as world events at EVERY protocol state.  The
# protocol's two one-way doors are what the search certifies:
#
#   no-double-serve   the source row never serves while an ACTIVATED
#                     destination copy is alive (cutover is final —
#                     abort must refuse after it; a resumed source
#                     plus an active dest would decode the session
#                     twice and fork the token stream)
#   no-token-loss     the source row is only retired once the
#                     destination copy is ACTIVATED (release before
#                     the activate ack — or after an abort/dest
#                     death — discards the only copy mid-generation)
#
# Pod deaths are availability loss, not protocol loss: a session on
# a dying pod dies with it exactly as it would without migration, so
# death alone never fires no-token-loss; the invariant fires only
# when the PROTOCOL retires the surviving copy.  Actions complete
# vacuously once their work is moot (abort honored, pod dead), so
# the plan always terminates and the livelock check stays sound.
#
# The ``abort_after_cutover`` / ``release_before_activate`` knobs
# exist ONLY for the seeded fixtures in test_lint_gate: a protocol
# that honors an abort after activation, or retires the source on
# splice success instead of the activate ack, is caught with a
# minimal trace.


class MigrationWorld:
    """Non-plan model state for the migration configuration."""

    def __init__(self, freeze_step, stream_step, cutover_step,
                 release_step,
                 abort_after_cutover: bool = False,
                 release_before_activate: bool = False):
        self.freeze_step = freeze_step
        self.stream_step = stream_step
        self.cutover_step = cutover_step
        self.release_step = release_step
        self.abort_after_cutover = abort_after_cutover
        self.release_before_activate = release_before_activate
        self.source_alive = True
        self.dest_alive = True
        self.source_state = "serving"   # serving | frozen | released
        self.dest_state = "none"        # none | spliced | active
        self.aborted = False
        # set when a protocol action discards the session's only
        # copy (retires the source with no activated destination) —
        # reachable only with broken knobs
        self.lost = False
        self.launch_overrides: Dict[str, Callable[[], None]] = {}
        self._plan: Optional[Plan] = None

    def bind(self, plan: Plan) -> "MigrationWorld":
        self._plan = plan
        return self

    # -- snapshot protocol -------------------------------------------

    def snapshot(self) -> tuple:
        return (self.source_alive, self.dest_alive, self.source_state,
                self.dest_state, self.aborted, self.lost)

    def restore(self, snap: tuple) -> None:
        (self.source_alive, self.dest_alive, self.source_state,
         self.dest_state, self.aborted, self.lost) = snap

    # -- model events -------------------------------------------------

    def events(self, harness: "PlanHarness"):
        # all three may land at ANY protocol state — that coverage is
        # the point of the configuration
        return [
            ("operator-abort", self._op_abort),
            ("source-pod-dies", self._source_dies),
            ("dest-pod-dies", self._dest_dies),
        ]

    def _op_abort(self) -> None:
        self.aborted = True
        if self.abort_after_cutover and self.source_alive \
                and self.source_state == "frozen":
            # SEEDED BUG: the abort handler unfreezes the source
            # without checking whether the destination already
            # activated — post-cutover this forks the stream
            self.source_state = "serving"

    def _source_dies(self) -> None:
        self.source_alive = False

    def _dest_dies(self) -> None:
        if not self.dest_alive:
            return
        self.dest_alive = False
        # its spliced pages / activation die with the pod
        self.dest_state = "none"

    def _resume_source(self) -> None:
        """Pre-cutover failure path: retire any splice, unfreeze the
        source.  NEVER past activation — cutover is a one-way door."""
        if self.dest_state == "active":
            return
        if self.dest_state == "spliced":
            self.dest_state = "none"  # abort_splice at the dest
        if self.source_alive and self.source_state == "frozen":
            self.source_state = "serving"

    # -- model actions (close over self; ActionStep passes None) ------

    def do_freeze(self, _scheduler) -> bool:
        if self.aborted or not self.source_alive \
                or self.source_state != "serving":
            return True  # moot: nothing to fence
        self.source_state = "frozen"
        return True

    def do_stream(self, _scheduler) -> bool:
        if self.dest_state != "none":
            return True  # already streamed
        if self.aborted or not self.source_alive \
                or not self.dest_alive or self.source_state != "frozen":
            self._resume_source()
            return True
        self.dest_state = "spliced"
        return True

    def do_cutover(self, _scheduler) -> bool:
        if self.dest_state == "active":
            return True  # already activated
        if self.release_before_activate and self.source_alive \
                and self.source_state == "frozen" \
                and self.dest_state == "spliced":
            # SEEDED BUG: retire the source row on splice success,
            # before the activate ack lands
            self.source_state = "released"
            if self.aborted or not self.dest_alive:
                self.dest_state = "none"
                self.lost = True
            else:
                self.dest_state = "active"
            return True
        if self.aborted or not self.dest_alive \
                or self.dest_state != "spliced":
            self._resume_source()
            return True
        self.dest_state = "active"
        return True

    def do_release(self, _scheduler) -> bool:
        if not self.source_alive or self.source_state != "frozen":
            return True  # nothing to retire
        if self.dest_state != "active":
            # activation never landed (abort, dest death): the only
            # legal continuation is keeping the source copy — the
            # ``aborted`` flag is deliberately NOT consulted here,
            # because post-cutover the move is final
            self._resume_source()
            return True
        self.source_state = "released"
        return True

    # -- invariants ----------------------------------------------------

    def invariants(self) -> List["Invariant"]:
        return [NoDoubleServe(), NoTokenLoss()]


class NoDoubleServe(Invariant):
    """The source row never serves while an activated destination
    copy is alive: both would decode the same session and the token
    streams fork — the exactly-once cutover contract."""

    name = "no-double-serve"

    def on_state(self, harness):
        world = harness.world
        if (world.source_alive and world.source_state == "serving"
                and world.dest_alive and world.dest_state == "active"):
            return (
                "source row serving while the activated destination "
                "copy is alive (the session decodes twice)"
            )
        return None


class NoTokenLoss(Invariant):
    """The source row is only retired once the destination copy is
    ACTIVATED: releasing against anything weaker (splice success, an
    abort, a dead dest) discards the session's only copy."""

    name = "no-token-loss"

    def on_state(self, harness):
        if harness.world.lost:
            return (
                "source row retired with no activated destination "
                "copy (mid-generation tokens discarded)"
            )
        return None


def _migration_plan(abort_after_cutover: bool = False,
                    release_before_activate: bool = False):
    freeze = ActionStep("freeze-session", lambda s: False)
    stream = ActionStep("stream-pages", lambda s: False)
    cutover = ActionStep("cutover-dest", lambda s: False)
    release = ActionStep("release-source", lambda s: False)
    world = MigrationWorld(
        freeze, stream, cutover, release,
        abort_after_cutover=abort_after_cutover,
        release_before_activate=release_before_activate,
    )
    freeze._action = world.do_freeze
    stream._action = world.do_stream
    cutover._action = world.do_cutover
    release._action = world.do_release
    phase = Phase(
        "migrate-session", [freeze, stream, cutover, release],
        SerialStrategy(),
    )
    plan = Plan("migration", [phase], SerialStrategy())
    world.bind(plan)
    return plan, world


def _migration_plan_strict():
    return _migration_plan()


# name -> (factory, step_interrupts): per-step interrupt verbs only
# where the extra state-space doubling buys new interleavings.
# ``gang-recovery``'s, ``autoscale``'s and ``migration``'s factories
# return (plan, world) — the checker folds the world's state into
# dedup snapshots and its events into the alphabet.
BUILTIN_CONFIGS: Dict[str, Tuple[Callable[[], Plan], bool]] = {
    "serial-2phase": (_serial_plan, False),
    "parallel": (_parallel_plan, True),
    "dependency-dag": (_dependency_plan, False),
    "canary": (_canary_plan, True),
    "gang-recovery": (_gang_recovery_plan, True),
    "multislice-recovery": (_multislice_recovery_plan_strict, False),
    "autoscale": (_autoscale_plan_strict, False),
    "migration": (_migration_plan_strict, True),
}


@dataclass
class PlanCheckSummary:
    results: List[CheckResult]

    @property
    def states_explored(self) -> int:
        return sum(r.states for r in self.results)

    @property
    def transitions(self) -> int:
        return sum(r.transitions for r in self.results)

    @property
    def violations(self) -> List[Violation]:
        return [v for r in self.results for v in r.violations]

    @property
    def ok(self) -> bool:
        return not self.violations

    def render(self) -> str:
        lines = []
        for r in self.results:
            flag = "" if not r.truncated else " (TRUNCATED)"
            lines.append(
                f"  {r.config}: {r.states} states, {r.transitions} "
                f"transitions, {r.complete_states} complete, "
                f"{len(r.violations)} violation(s){flag}"
            )
        for v in self.violations:
            lines.append(v.render())
        return "\n".join(lines)


def check_all(
    max_states: int = 200_000,
    configs: Optional[
        Dict[str, Tuple[Callable[[], Plan], bool]]
    ] = None,
) -> PlanCheckSummary:
    """Run every built-in configuration; the CI gate entry point."""
    results = []
    for name, (factory, step_interrupts) in (
        configs or BUILTIN_CONFIGS
    ).items():
        results.append(check_plan(
            factory, max_states=max_states, config_name=name,
            step_interrupts=step_interrupts,
        ))
    return PlanCheckSummary(results)
