"""Serving front door (ISSUE 12): routing, affinity, drain, failover.

Four layers of coverage:

* AFFINITY units (no engines): the page-aligned chain keys mirror the
  paging intern construction (full pages only, last page capped), and
  the bounded affinity map LRU-evicts and drops a dead pod's claims.

* CORE ROUTER against scripted pods (no engines): least-loaded
  placement off fresh gauges, the staleness gate (a wedged pod's
  last-good numbers never steer placement), drain exclusion,
  affinity-follows-the-cache with the load-slack override, honest
  retry budgets, and application errors passing through un-retried.

* FAILOVER against REAL slot engines (the satellite): kill a pod
  mid-stream — queued and in-flight requests complete on survivors,
  every greedy continuation arrives exactly once (no duplicates), and
  the dead/drained pod receives zero new admissions.

* FRONT DOOR over real sockets: discovery against a scripted
  endpoint body (generation-stamped refresh skips quiet rebuilds),
  /generate proxying with pod-error pass-through, /stats gauges, and
  the drain verbs.
"""

import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from dcos_commons_tpu.router import (
    AffinityMap,
    NoPodAvailableError,
    PodTransportError,
    RequestRouter,
    prefix_chain_keys,
)
from dcos_commons_tpu.serve.engine import SlotEngine

# -- the deterministic chain model (test_continuous_batching's fake) --

_V = 97


def _chain_first(prompt):
    return (sum(prompt) * 31 + len(prompt)) % _V


def _chain_next(tok, pos):
    return (tok * 7 + pos * 3 + 1) % _V


def _chain_oracle(prompt, n, eos=None):
    out = [_chain_first(prompt)]
    pos = len(prompt)
    while len(out) < n and (eos is None or out[-1] != eos):
        out.append(_chain_next(out[-1], pos))
        pos += 1
    if eos is not None and eos in out:
        out = out[: out.index(eos) + 1]
    return out


class FakeModel:
    def __init__(self, slots):
        self.slots = slots

    def prefill(self, padded, slot, true_len, temp, seed):
        return _chain_first([int(t) for t in padded[0, :true_len]])

    def decode(self, tok, pos, temps, seeds, n_active):
        return np.asarray(
            [_chain_next(int(t), int(p)) for t, p in zip(tok, pos)],
            np.int32,
        )


# -- affinity units ----------------------------------------------------


def test_prefix_chain_keys_page_aligned_and_capped():
    p = 4
    a = [1, 2, 3, 4, 5, 6, 7, 8, 9]
    # 9 tokens / page 4: limit = (9-1)//4 = 2 full pages participate
    keys = prefix_chain_keys(a, p)
    assert len(keys) == 2
    # identical page-aligned prefix -> identical chain
    b = [1, 2, 3, 4, 5, 6, 7, 8, 42]
    assert prefix_chain_keys(b, p) == keys
    # divergence in the SECOND page breaks only the deeper key
    c = [1, 2, 3, 4, 9, 9, 9, 9, 1]
    keys_c = prefix_chain_keys(c, p)
    assert keys_c[0] == keys[0] and keys_c[1] != keys[1]
    # an exactly-one-page prompt is capped to ZERO keys (>= 1 token
    # always prefills privately — the paging hit cap, mirrored)
    assert prefix_chain_keys([1, 2, 3, 4], p) == []
    assert prefix_chain_keys([1, 2, 3, 4, 5], p) != []
    assert prefix_chain_keys([], p) == []


def test_affinity_map_records_lru_evicts_and_drops_dead_pods():
    m = AffinityMap(capacity=3)
    m.record([101, 102], "pod-a")
    m.record([201], "pod-b")
    assert m.lookup([101, 102]) == ("pod-a", 2)
    assert m.lookup([101, 999]) == ("pod-a", 1)  # deepest known wins
    assert m.lookup([999]) == (None, 0)
    # capacity 3 full; recording a 4th evicts the LRU entry (201 was
    # refreshed by its lookup? no — 101/102 were looked up later)
    m.record([301], "pod-c")
    assert len(m) == 3
    assert m.lookup([201]) == (None, 0)  # the oldest claim evicted
    # a dead pod's claims vanish wholesale
    assert m.evict_pod("pod-a") == 2
    assert m.lookup([101, 102]) == (None, 0)


# -- core router against scripted pods ---------------------------------


def _router(send, pods=("a", "b"), policy="affinity", **kw):
    r = RequestRouter(send, page_tokens=4, policy=policy,
                      stale_after_s=5.0, **kw)
    r.update_pods({name: {"address": f"host-{name}:80"}
                   for name in pods}, generation="g1")
    return r


def _fresh(queue_depth=0, active=0, **kw):
    out = {"queue_depth": queue_depth, "active_slots": active,
           "free_slots": 8, "stats_age_s": 0.0}
    out.update(kw)
    return out


def test_router_least_loaded_placement_on_fresh_gauges():
    r = _router(lambda n, a, req: [[0]], policy="least-loaded")
    r.observe_stats("a", _fresh(queue_depth=5, active=3))
    r.observe_stats("b", _fresh(queue_depth=0, active=1))
    assert r.route([1, 2, 3]) == "b"
    r.observe_stats("b", _fresh(queue_depth=9, active=8))
    assert r.route([1, 2, 3]) == "a"


def test_router_staleness_gate_demotes_wedged_pod():
    """A pod whose engine loop stopped ticking reports a growing
    stats_age_s with last-good (idle-looking) gauges: it must rank
    behind any fresh pod regardless of those numbers."""
    r = _router(lambda n, a, req: [[0]], policy="least-loaded")
    # pod a LOOKS idle but its loop is wedged; pod b is honestly busy
    r.observe_stats("a", _fresh(queue_depth=0, active=0,
                                stats_age_s=60.0))
    r.observe_stats("b", _fresh(queue_depth=6, active=8))
    assert r.route([1, 2, 3]) == "b"
    stats = r.stats()
    assert stats["router_stale_routing_rounds"] == 0
    # ...and a poll that went dark ages out the same way
    r2 = _router(lambda n, a, req: [[0]], policy="least-loaded")
    r2.observe_stats("a", _fresh(queue_depth=0), now=time.monotonic() - 60)
    r2.observe_stats("b", _fresh(queue_depth=6))
    assert r2.route([1]) == "b"


def test_router_drain_excludes_new_admissions():
    picks = []
    r = _router(lambda n, a, req: picks.append(n) or [[0]])
    r.observe_stats("a", _fresh())
    r.observe_stats("b", _fresh())
    assert r.drain("a")
    for _ in range(4):
        r.submit([1, 2], 2)
    assert set(picks) == {"b"}
    stats = r.stats()
    assert stats["router_pods_draining"] == 1
    # undrain re-admits
    r.undrain("a")
    picks.clear()
    r.observe_stats("a", _fresh(queue_depth=0))
    r.observe_stats("b", _fresh(queue_depth=9))
    r.submit([1, 2], 2)
    assert picks == ["a"]
    # draining EVERY pod is a clean 503, not a hang
    r.drain("a"), r.drain("b")
    with pytest.raises(NoPodAvailableError):
        r.submit([1, 2], 2)


def test_router_affinity_follows_shared_prefix_and_yields_to_load():
    picks = []
    r = _router(lambda n, a, req: picks.append(n) or [[0]],
                affinity_slack=4.0)
    r.observe_stats("a", _fresh())
    r.observe_stats("b", _fresh())
    sys_prefix = list(range(1, 9))  # two full pages of 4
    first = sys_prefix + [50]
    r.submit(first, 2)
    owner = picks[0]
    # every shared-prefix request follows the owner...
    for i in range(5):
        r.submit(sys_prefix + [60 + i], 2)
    assert set(picks) == {owner}
    assert r.stats()["router_affinity_hits"] >= 5
    # ...until the owner is overloaded past the slack: load wins
    other = "b" if owner == "a" else "a"
    r.observe_stats(owner, _fresh(queue_depth=20, active=8))
    r.observe_stats(other, _fresh(queue_depth=0))
    picks.clear()
    r.submit(sys_prefix + [99], 2)
    assert picks == [other]
    assert r.stats()["router_affinity_overridden"] >= 1


def test_router_failover_honest_budget_and_app_error_passthrough():
    calls = []

    def send(name, address, request):
        calls.append(name)
        if name == "a":
            raise PodTransportError("connection reset")
        return [[7, 7]]

    r = _router(send, retry_budget=2)
    r.observe_stats("a", _fresh(queue_depth=0))
    r.observe_stats("b", _fresh(queue_depth=5))
    # a is least-loaded and picked first; its death fails over to b
    assert r.submit([1, 2], 2) == [7, 7]
    assert calls == ["a", "b"]
    stats = r.stats()
    assert stats["router_failovers"] == 1
    assert stats["router_pods_failed"] == 1
    # a stays off the rotation until a FRESH snapshot readmits it
    assert r.route([1, 2]) == "b"
    r.observe_stats("a", _fresh())
    assert r.stats()["router_pods_failed"] == 0

    # budget exhaustion surfaces the transport error (502), honestly
    def always_dead(name, address, request):
        raise PodTransportError("down")

    r2 = _router(always_dead, retry_budget=1)
    r2.observe_stats("a", _fresh())
    r2.observe_stats("b", _fresh())
    with pytest.raises(PodTransportError, match="budget 1 exhausted"):
        r2.submit([1, 2], 2)

    # an application error (the pod ANSWERED) is never retried
    attempts = []

    def app_error(name, address, request):
        attempts.append(name)
        raise ValueError("prompt too long")

    r3 = _router(app_error, retry_budget=2)
    r3.observe_stats("a", _fresh())
    r3.observe_stats("b", _fresh())
    with pytest.raises(ValueError):
        r3.submit([1, 2], 2)
    assert len(attempts) == 1


def test_router_generation_stamped_refresh_skips_quiet_rebuilds():
    r = RequestRouter(lambda n, a, req: [[0]], page_tokens=4)
    assert r.update_pods({"a": {"address": "h:1"}}, generation="g1")
    assert not r.update_pods({"a": {"address": "h:1"}}, generation="g1")
    assert r.update_pods({"a": {"address": "h:1"},
                          "b": {"address": "h:2"}}, generation="g2")
    assert r.pods() == ["a", "b"]
    # discovery-driven drain: a pausing backend stops admitting
    r.update_pods({"a": {"address": "h:1", "draining": True},
                   "b": {"address": "h:2"}}, generation="g3")
    r.observe_stats("a", _fresh())
    r.observe_stats("b", _fresh())
    assert r.route([1]) == "b"
    # a vanished pod leaves the set (and its affinity claims)
    r.update_pods({"b": {"address": "h:2"}}, generation="g4")
    assert r.pods() == ["b"]


def test_router_operator_drain_survives_discovery_refresh():
    """An operator drain is STICKY: a discovery refresh reporting
    the pod healthy (it IS still TASK_RUNNING scheduler-side while
    the runbook waits for in-flight work to finish) must not quietly
    re-admit it mid-decommission.  Only undrain() clears the verb."""
    r = _router(lambda n, a, req: [[0]])
    r.observe_stats("a", _fresh())
    r.observe_stats("b", _fresh())
    assert r.drain("a")
    # discovery refresh: scheduler still reports a healthy, undrained
    # backend set under a NEW generation (unrelated fleet churn)
    r.update_pods({"a": {"address": "host-a:80", "draining": False},
                   "b": {"address": "host-b:80"}}, generation="g2")
    r.observe_stats("a", _fresh())
    assert r.route([1, 2]) == "b"
    assert r.stats()["router_pods_draining"] == 1
    # the bare-address fallback (no generation: EVERY poll rebuilds)
    # must not undo it either
    r.update_pods({"a": {"address": "host-a:80"},
                   "b": {"address": "host-b:80"}})
    assert r.route([1, 2]) == "b"
    # only the operator verb clears the operator flag
    r.undrain("a")
    r.observe_stats("a", _fresh(queue_depth=0))
    r.observe_stats("b", _fresh(queue_depth=9))
    assert r.route([1, 2]) == "a"


# -- failover against REAL engines (the satellite test) ----------------


class EnginePod:
    """One in-process 'serve pod': a SlotEngine over the chain model,
    dialable through a send() that can be killed mid-stream."""

    def __init__(self, name, slots=4):
        self.name = name
        self.model = FakeModel(slots)
        self.engine = SlotEngine(
            self.model.prefill, self.model.decode, slots, 64, 32,
            queue_timeout_s=60,
        )
        self.killed = threading.Event()
        self.admitted = 0
        self.completed = 0
        self._lock = threading.Lock()

    def send(self, request):
        if self.killed.is_set():
            raise PodTransportError(f"{self.name} is dead")
        with self._lock:
            self.admitted += 1
        result = self.engine.submit(
            request["tokens"], request["max_new_tokens"],
            temperature=request.get("temperature", 0.0),
            eos_id=request.get("eos"),
        )
        if self.killed.is_set():
            # died before the response left the pod: the bytes never
            # reached the router — exactly the mid-stream kill case
            raise PodTransportError(f"{self.name} died mid-stream")
        with self._lock:
            self.completed += 1
        return result

    def stop(self):
        self.engine.stop()


def test_router_pod_kill_mid_stream_completes_on_survivors():
    """The satellite: kill a pod mid-stream; queued + in-flight
    requests all complete on the survivors, each greedy continuation
    arrives exactly once, and the dead pod gets zero admissions after
    the kill."""
    pods = {name: EnginePod(name) for name in ("a", "b", "c")}
    router = RequestRouter(
        lambda name, addr, req: pods[name].send(req),
        page_tokens=4, stale_after_s=5.0, retry_budget=2,
    )
    router.update_pods(
        {n: {"address": f"{n}:80"} for n in pods}, generation="g1"
    )
    for name, pod in pods.items():
        router.observe_stats(name, pod.engine.stats())

    n_requests = 24
    jobs = [([i + 1, i + 2, i + 3], 6) for i in range(n_requests)]
    results = [None] * n_requests
    errors = []
    kill_at = threading.Event()

    def client(i):
        if i == n_requests // 2:
            kill_at.set()
        try:
            results[i] = router.submit(jobs[i][0], jobs[i][1])
        except Exception as e:  # noqa: BLE001 — surfaced via assert
            errors.append((i, e))

    def killer():
        assert kill_at.wait(30)
        pods["a"].killed.set()  # mid-stream: in-flight sends now die

    threads = [threading.Thread(target=killer)] + [
        threading.Thread(target=client, args=(i,))
        for i in range(n_requests)
    ]
    try:
        for t in threads:
            t.start()
            time.sleep(0.002)  # staggered: some in flight at the kill
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors
        # every continuation correct, exactly once
        for (prompt, n), result in zip(jobs, results):
            assert result == _chain_oracle(prompt, n)
        # no silent duplication: completions across pods == requests
        completed = sum(p.completed for p in pods.values())
        assert completed == n_requests
        # the dead pod is out of rotation: admissions stopped at the
        # kill (failed mark), and new traffic avoids it entirely
        admitted_at_kill = pods["a"].admitted
        for i in range(4):
            router.submit([90 + i], 3)
        assert pods["a"].admitted == admitted_at_kill
        stats = router.stats()
        assert stats["router_failovers"] >= 1
        assert stats["requests_completed"] == n_requests + 4
    finally:
        for pod in pods.values():
            pod.stop()


# -- the HTTP front door over real sockets -----------------------------


class HttpPod:
    """A minimal real-socket serve pod: /generate + /stats."""

    def __init__(self, name):
        self.name = name
        self.model = FakeModel(4)
        self.engine = SlotEngine(
            self.model.prefill, self.model.decode, 4, 64, 32,
            queue_timeout_s=30,
        )
        engine = self.engine

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _reply(self, code, body):
                payload = json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):
                self._reply(200, engine.stats())

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length))
                try:
                    out = engine.submit(
                        body["tokens"], body["max_new_tokens"],
                    )
                except ValueError as e:
                    self._reply(400, {"error": str(e)})
                    return
                self._reply(200, {"tokens": out})

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.thread = threading.Thread(
            target=self.server.serve_forever, daemon=True
        )
        self.thread.start()

    @property
    def address(self):
        host, port = self.server.server_address[:2]
        return f"{host}:{port}"

    def stop(self):
        self.server.shutdown()
        self.server.server_close()
        self.engine.stop()


def test_frontdoor_end_to_end_over_http(tmp_path):
    from dcos_commons_tpu.router.frontdoor import RouterServer

    pods = [HttpPod("pod-0"), HttpPod("pod-1")]
    discovery_calls = [0]

    def discover():
        discovery_calls[0] += 1
        return {
            "name": "vip:inference",
            "generation": "gen-1",
            "address": sorted(p.address for p in pods),
            "backends": [
                {"address": p.address, "task": p.name,
                 "state": "TASK_RUNNING", "ready": True,
                 "draining": False}
                for p in pods
            ],
        }

    stats_path = str(tmp_path / "servestats.json")
    server = RouterServer(
        "http://unused", discover=discover, port=0,
        host="127.0.0.1", poll_interval_s=0.2,
        stats_path=stats_path, page_tokens=4, log=None,
    )
    server.start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        # generate through the front door: greedy == direct oracle
        body = json.dumps(
            {"tokens": [[1, 2, 3], [4, 5]], "max_new_tokens": 5}
        ).encode()
        req = urllib.request.Request(
            f"{base}/generate", data=body, method="POST",
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            out = json.loads(resp.read())
        assert out["tokens"] == [
            _chain_oracle([1, 2, 3], 5), _chain_oracle([4, 5], 5),
        ]
        # router gauges over HTTP, watcher-compatible keys included
        with urllib.request.urlopen(f"{base}/stats", timeout=10) as resp:
            stats = json.loads(resp.read())
        assert stats["router_pods"] == 2
        assert stats["requests_completed"] == 2
        assert "stats_age_s" in stats and "t" in stats
        assert stats["http_port"] == server.port
        # generation-stamped refresh: polls happened, ONE rebuild
        deadline = time.monotonic() + 5
        while discovery_calls[0] < 3 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert discovery_calls[0] >= 3
        assert server.router.stats()["router_generation"] == "gen-1"
        # pod application errors pass through with their status
        bad = json.dumps(
            {"tokens": [[1] * 99], "max_new_tokens": 5}
        ).encode()
        req = urllib.request.Request(
            f"{base}/generate", data=bad, method="POST",
            headers={"Content-Type": "application/json"},
        )
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=30)
        assert exc.value.code == 400
        # drain verb: the drained pod stops admitting
        req = urllib.request.Request(
            f"{base}/drain?pod=pod-0", data=b"", method="POST"
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert json.loads(resp.read())["draining"] is True
        with urllib.request.urlopen(f"{base}/pods", timeout=10) as resp:
            pods_body = json.loads(resp.read())
        assert pods_body["pods"]["pod-0"]["draining"] is True
        # the router's sandbox mirror exists for the scheduler merge
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            try:
                with open(stats_path) as f:
                    mirrored = json.load(f)
                if mirrored.get("router_pods") == 2:
                    break
            except (OSError, ValueError):
                pass
            time.sleep(0.05)
        assert mirrored["router_pods"] == 2
    finally:
        server.stop()
        for pod in pods:
            pod.stop()


# -- engine stats_age_s (the ISSUE 12 serve-side stamp) ----------------


def test_engine_stats_age_tracks_loop_liveness():
    gate = threading.Event()  # never set: decode wedges

    class WedgedModel(FakeModel):
        def decode(self, tok, pos, temps, seeds, n_active):
            assert gate.wait(30)
            return super().decode(tok, pos, temps, seeds, n_active)

    model = WedgedModel(2)
    engine = SlotEngine(model.prefill, model.decode, 2, 64, 32,
                        queue_timeout_s=60)
    try:
        # idle: trivially responsive, age pinned at zero
        assert engine.stats()["stats_age_s"] == 0.0
        worker = threading.Thread(
            target=lambda: engine.submit([[1, 2]], 4), daemon=True
        )
        worker.start()
        deadline = time.monotonic() + 10
        while engine.stats()["active_slots"] < 1 and \
                time.monotonic() < deadline:
            time.sleep(0.01)
        time.sleep(0.25)  # the loop is now stuck inside decode
        age = engine.stats()["stats_age_s"]
        assert age >= 0.2, f"wedged loop not aging: {age}"
        gate.set()
        worker.join(timeout=30)
        assert not worker.is_alive()
        assert engine.stats()["stats_age_s"] == 0.0  # idle again
    finally:
        gate.set()
        engine.stop()


def test_engine_extra_stats_annotation_rides_every_snapshot():
    model = FakeModel(2)
    engine = SlotEngine(model.prefill, model.decode, 2, 64, 32,
                        extra_stats={"http_port": 4242})
    try:
        assert engine.stats()["http_port"] == 4242
        engine.annotate_stats(zone="z1")
        stats = engine.stats()
        assert stats["http_port"] == 4242 and stats["zone"] == "z1"
    finally:
        engine.stop()
