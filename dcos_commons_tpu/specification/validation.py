"""Config-update validators: what may change between target configs.

Reference: sdk/scheduler/.../config/validate/ (19 validator classes,
run by DefaultConfigurationUpdater.updateConfiguration,
config/DefaultConfigurationUpdater.java:159).  Each validator compares
the previous target spec against the candidate and emits errors; any
error keeps the old target active and surfaces via /v1/plans errors.

TPU-first addition: TpuTopologyCannotChange — you cannot reshape a
live slice's ICI topology by rolling update; that requires pod
replace (SURVEY.md section 2 build plan stage 2).
"""

from __future__ import annotations

import inspect
import re
from dataclasses import dataclass
from typing import Callable, List, Optional

from dcos_commons_tpu.specification.specs import ServiceSpec


class ConfigValidationError(Exception):
    def __init__(self, errors: List[str]):
        super().__init__("; ".join(errors))
        self.errors = errors


Validator = Callable[[Optional[ServiceSpec], ServiceSpec], List[str]]


@dataclass
class ValidationContext:
    """Deployment-state context some validators need (reference:
    validators like ServiceRoleCannotChangeOnIncompleteDeployment take
    a StateStore; ours take this snapshot instead so the validator
    functions stay pure).  ``None`` fields mean "unknown — skip the
    check" so pure two-argument callers are unaffected."""

    # has the initial deploy plan ever completed?
    deployment_completed: Optional[bool] = None
    # is a secrets provider wired (SECRETS_DIR / set_secrets_provider)?
    secrets_provider_present: Optional[bool] = None
    # is the control plane authenticated (cluster bearer token set)?
    auth_token_present: Optional[bool] = None


def service_name_cannot_change(old, new):
    """Reference: config/validate/ServiceNameCannotContainDoubleUnderscores
    + the implicit identity check in DefaultConfigurationUpdater."""
    errs = []
    if "__" in new.name:
        errs.append(f"service name {new.name!r} may not contain '__'")
    if old is not None and old.name != new.name:
        errs.append(f"service name cannot change: {old.name!r} -> {new.name!r}")
    return errs


def user_cannot_change(old, new):
    """Reference: config/validate/UserCannotChange.java."""
    if old is not None and old.user and old.user != new.user:
        return [f"user cannot change: {old.user!r} -> {new.user!r}"]
    return []


def region_cannot_change(old, new):
    """Reference: config/validate/RegionCannotChange.java."""
    if old is not None and old.region != new.region:
        return [f"region cannot change: {old.region!r} -> {new.region!r}"]
    return []


def pod_specs_cannot_shrink(old, new):
    """Reference: config/validate/PodSpecsCannotShrink.java — pod count
    may only shrink via explicit decommission (allow_decommission)."""
    errs = []
    if old is None:
        return errs
    new_pods = {p.type: p for p in new.pods}
    for old_pod in old.pods:
        new_pod = new_pods.get(old_pod.type)
        if new_pod is None:
            if not old_pod.allow_decommission:
                errs.append(f"pod {old_pod.type!r} cannot be removed")
        elif new_pod.count < old_pod.count and not old_pod.allow_decommission:
            errs.append(
                f"pod {old_pod.type!r} count cannot shrink "
                f"{old_pod.count} -> {new_pod.count} without allow-decommission"
            )
    return errs


def _effective_volumes(pod, task):
    """Pod-level volumes merged over the task's own, keyed by path —
    the view the evaluator places with.  Comparing EFFECTIVE volumes
    keeps target configs stored before the yaml-spec merge (tasks
    without the pod volume copied in) compatible with re-renders after
    it."""
    merged = {v.container_path: v for v in pod.volumes}
    merged.update({v.container_path: v for v in task.volumes})
    return tuple(sorted(merged.items()))


def task_volumes_cannot_change(old, new):
    """Reference: config/validate/TaskVolumesCannotChange.java."""
    errs = []
    if old is None:
        return errs
    new_pods = {p.type: p for p in new.pods}
    for old_pod in old.pods:
        new_pod = new_pods.get(old_pod.type)
        if new_pod is None:
            continue
        if tuple(old_pod.volumes) != tuple(new_pod.volumes):
            errs.append(f"pod {old_pod.type!r} volumes cannot change")
        old_tasks = {t.name: t for t in old_pod.tasks}
        for new_task in new_pod.tasks:
            old_task = old_tasks.get(new_task.name)
            if old_task and _effective_volumes(old_pod, old_task) != \
                    _effective_volumes(new_pod, new_task):
                errs.append(
                    f"task {old_pod.type}-{new_task.name} volumes cannot change"
                )
    return errs


def tpu_topology_cannot_change(old, new):
    """TPU-first: the ICI topology of a live pod cannot change by
    rolling update — a pjit mesh is one XLA program over a fixed
    device mesh.  Changing generation/topology requires pod replace."""
    errs = []
    if old is None:
        return errs
    new_pods = {p.type: p for p in new.pods}
    for old_pod in old.pods:
        new_pod = new_pods.get(old_pod.type)
        if new_pod is None or old_pod.tpu is None:
            continue
        if new_pod.tpu is None:
            errs.append(f"pod {old_pod.type!r} cannot drop its tpu block")
        elif (
            old_pod.tpu.generation != new_pod.tpu.generation
            or old_pod.tpu.topology != new_pod.tpu.topology
            or old_pod.tpu.slices != new_pod.tpu.slices
        ):
            errs.append(
                f"pod {old_pod.type!r} TPU topology cannot change "
                f"({old_pod.tpu.generation}/{old_pod.tpu.topology}"
                f"x{old_pod.tpu.slices} -> "
                f"{new_pod.tpu.generation}/{new_pod.tpu.topology}"
                f"x{new_pod.tpu.slices}); use pod replace"
            )
    return errs


def gang_pods_need_topology(old, new):
    """A gang pod with a multi-host topology must have count matching
    slices x the per-slice host count (total_chips / chips_per_host)."""
    errs = []
    for pod in new.pods:
        if pod.tpu is None:
            continue
        if not pod.tpu.topology:
            if pod.tpu.slices > 1:
                # a slices request without a topology (or gang) would
                # silently take the per-instance placement path with
                # no slice contract — reject, don't drop on the floor
                errs.append(
                    f"pod {pod.type!r}: tpu slices: {pod.tpu.slices} "
                    "requires a topology (the per-slice ICI shape)"
                )
            continue
        if pod.tpu.slices > 1 and not pod.gang:
            errs.append(
                f"pod {pod.type!r}: tpu slices: {pod.tpu.slices} "
                "requires gang: true (sub-gangs place atomically)"
            )
            continue
        total = pod.tpu.total_chips
        per_host = pod.tpu.chips_per_host
        if pod.tpu.slices < 1:
            errs.append(f"pod {pod.type!r}: slices must be >= 1")
            continue
        if total % per_host != 0:
            errs.append(
                f"pod {pod.type!r}: topology {pod.tpu.topology} total chips "
                f"{total} not divisible by chips-per-host {per_host}"
            )
            continue
        hosts = (total // per_host) * pod.tpu.slices
        if pod.count != hosts:
            errs.append(
                f"pod {pod.type!r}: count {pod.count} != {hosts} hosts implied "
                f"by {pod.tpu.slices} slice(s) of topology "
                f"{pod.tpu.topology} at {per_host} chips/host"
            )
    return errs


def placement_rules_must_parse(old, new):
    """A bad placement string is a CONFIG error, not a runtime crash in
    the offer cycle (reference: InvalidPlacementRule records parse
    failures so the scheduler surfaces them instead of wedging)."""
    from dcos_commons_tpu.offer.placement import parse_placement

    errs = []
    for pod in new.pods:
        try:
            parse_placement(pod.placement)
        except ValueError as e:
            errs.append(f"pod {pod.type!r}: bad placement: {e}")
    return errs


_DNS_LABEL = re.compile(r"^[a-z0-9]([a-z0-9-]*[a-z0-9])?$")


def service_name_cannot_break_dns(old, new):
    """Reference: config/validate/ServiceNameCannotBreakDNS.java — task
    DNS names are derived from the service name, so every /-separated
    folder component must be a valid DNS label."""
    errs = []
    for part in new.name.strip("/").split("/"):
        if len(part) > 63 or not _DNS_LABEL.match(part):
            errs.append(
                f"service name component {part!r} is not a valid DNS "
                "label (lowercase alphanumerics and dashes, max 63 chars)"
            )
    return errs


def zone_cannot_change(old, new):
    """Reference: config/validate/ZoneValidator.java — zone-aware
    placement may only transition unset->off, on->on, off->off; here
    the service-level zone pin follows RegionCannotChange semantics."""
    if old is not None and old.zone != new.zone:
        return [f"zone cannot change: {old.zone!r} -> {new.zone!r}"]
    return []


def _placement_references_zone(placement: str) -> bool:
    """Walk the PARSED rule tree for field_name == 'zone' terms — a
    substring test would misfire on e.g. hostname:like:tpu-zone1-.*."""
    from dcos_commons_tpu.offer.placement import parse_placement

    try:
        rule = parse_placement(placement)
    except ValueError:
        return False  # placement_rules_must_parse reports this one
    stack = [rule]
    while stack:
        node = stack.pop()
        if getattr(node, "field_name", None) == "zone":
            return True
        stack.extend(getattr(node, "rules", []))
        child = getattr(node, "rule", None)
        if child is not None:
            stack.append(child)
    return False


def zone_placement_cannot_change(old, new):
    """Reference: ZoneValidator.java:14-21 — a pod cannot start or stop
    *referencing zones* in its placement rules on update (the running
    tasks were placed without zone bookkeeping, so the scheduler cannot
    retroactively enforce it)."""
    errs = []
    if old is None:
        return errs
    new_pods = {p.type: p for p in new.pods}
    for old_pod in old.pods:
        new_pod = new_pods.get(old_pod.type)
        if new_pod is None:
            continue
        old_zonal = _placement_references_zone(old_pod.placement)
        new_zonal = _placement_references_zone(new_pod.placement)
        if old_zonal != new_zonal:
            errs.append(
                f"pod {old_pod.type!r} cannot "
                f"{'start' if new_zonal else 'stop'} referencing zones "
                "in placement after deployment"
            )
    return errs


def pod_networks_cannot_change(old, new):
    """Reference: config/validate/PodSpecsCannotChangeNetworkRegime.java
    — a pod on the host network holds real host ports; moving it onto a
    virtual network (or back) would strand those reservations."""
    errs = []
    if old is None:
        return errs
    new_pods = {p.type: p for p in new.pods}
    for old_pod in old.pods:
        new_pod = new_pods.get(old_pod.type)
        if new_pod is None:
            continue
        if sorted(old_pod.networks) != sorted(new_pod.networks):
            errs.append(
                f"pod {old_pod.type!r} networks cannot change "
                f"{sorted(old_pod.networks)} -> {sorted(new_pod.networks)}"
            )
    return errs


def pre_reserved_role_cannot_change(old, new):
    """Reference: config/validate/PreReservationCannotChange.java —
    reservations are stamped with the pre-reserved role at create time;
    a different role cannot adopt them."""
    errs = []
    if old is None:
        return errs
    new_pods = {p.type: p for p in new.pods}
    for old_pod in old.pods:
        new_pod = new_pods.get(old_pod.type)
        if new_pod is None:
            continue
        if old_pod.pre_reserved_role != new_pod.pre_reserved_role:
            errs.append(
                f"pod {old_pod.type!r} pre-reserved-role cannot change "
                f"{old_pod.pre_reserved_role!r} -> "
                f"{new_pod.pre_reserved_role!r}"
            )
    return errs


def task_env_cannot_change_for_finished(old, new):
    """Reference: config/validate/TaskEnvCannotChange.java — the env of
    a ONCE/FINISH-goal task that already ran defines what it *did*;
    changing it would silently not re-run with the new values."""
    from dcos_commons_tpu.specification.specs import GoalState

    errs = []
    if old is None:
        return errs
    new_pods = {p.type: p for p in new.pods}
    for old_pod in old.pods:
        new_pod = new_pods.get(old_pod.type)
        if new_pod is None:
            continue
        new_tasks = {t.name: t for t in new_pod.tasks}
        for old_task in old_pod.tasks:
            new_task = new_tasks.get(old_task.name)
            if new_task is None:
                continue
            if (
                old_task.goal in (GoalState.ONCE, GoalState.FINISH)
                and old_task.env != new_task.env
            ):
                errs.append(
                    f"task {old_pod.type}-{old_task.name} "
                    f"(goal {old_task.goal.value}) env cannot change; "
                    "use pod replace to re-run it"
                )
    return errs


def gang_flag_cannot_change(old, new):
    """TPU-first: gang scheduling is burned into how a pod's instances
    were placed (atomically, one slice) — toggling it needs replace."""
    errs = []
    if old is None:
        return errs
    new_pods = {p.type: p for p in new.pods}
    for old_pod in old.pods:
        new_pod = new_pods.get(old_pod.type)
        if new_pod is not None and old_pod.gang != new_pod.gang:
            errs.append(
                f"pod {old_pod.type!r} cannot toggle gang scheduling "
                f"({old_pod.gang} -> {new_pod.gang}); use pod replace"
            )
    return errs


_KNOWN_GENERATIONS = ("v4", "v5e", "v5p", "v6e")


def tpu_generation_supported(old, new):
    """Reference: PodSpecsCannotUseUnsupportedFeatures.java /
    TaskSpecsCannotUseUnsupportedFeatures.java — a spec demanding a
    capability the substrate lacks is a config error, not a forever-
    pending deploy plan.  Here: the TPU generation must be one the
    inventory model understands."""
    errs = []
    for pod in new.pods:
        if pod.tpu is not None and pod.tpu.generation not in _KNOWN_GENERATIONS:
            errs.append(
                f"pod {pod.type!r}: unknown TPU generation "
                f"{pod.tpu.generation!r} (supported: "
                f"{', '.join(_KNOWN_GENERATIONS)})"
            )
    return errs


def role_cannot_change_on_incomplete_deployment(old, new, context=None):
    """Reference: ServiceRoleCannotChangeOnIncompleteDeployment.java —
    a role migration is only safe once the initial deployment finished
    (mid-deploy, half the reservations would carry the old role)."""
    if old is None or old.role == new.role:
        return []
    completed = context.deployment_completed if context else None
    if completed is None:
        # no deployment-state context: allow (the completed-deploy
        # role-migration path is legitimate and must not be blocked)
        return []
    if not completed:
        return [
            f"service role cannot change ({old.role!r} -> {new.role!r}) "
            "while the initial deployment is incomplete"
        ]
    return []


def secrets_require_provider(old, new, context=None):
    """Reference: config/validate/TLSRequiresServiceAccount.java — a
    spec whose tasks need credentials must fail CONFIGURATION when the
    backing credential plane is absent, not the eventual launch."""
    present = context.secrets_provider_present if context else None
    if present is None or present:
        return []
    errs = []
    for pod in new.pods:
        if pod.secrets:
            errs.append(
                f"pod {pod.type!r} references secrets but no secrets "
                "provider is configured (set SECRETS_DIR / --secrets-dir "
                "or SchedulerBuilder.set_secrets_provider)"
            )
    return errs


def tls_requires_credentials(old, new, context=None):
    """Reference: config/validate/TLSRequiresServiceAccount.java — a
    spec requesting transport encryption needs the credential plane
    behind it: per-task certs are only trustworthy when the control
    plane itself is authenticated (agents pull cert material over it),
    so TLS without a cluster auth token is a misconfiguration caught
    at CONFIGURATION time, not at launch."""
    present = context.auth_token_present if context else None
    if present is None or present:
        return []
    errs = []
    for pod in new.pods:
        for task in pod.tasks:
            if task.transport_encryption:
                errs.append(
                    f"task {pod.type}/{task.name} requests "
                    "transport-encryption but the control plane has no "
                    "auth token (set AUTH_TOKEN/--auth-token-file; the "
                    "reference requires a service account for TLS the "
                    "same way)"
                )
    return errs


def default_validators() -> List[Validator]:
    return [
        service_name_cannot_change,
        service_name_cannot_break_dns,
        user_cannot_change,
        region_cannot_change,
        zone_cannot_change,
        zone_placement_cannot_change,
        pod_specs_cannot_shrink,
        task_volumes_cannot_change,
        task_env_cannot_change_for_finished,
        pod_networks_cannot_change,
        pre_reserved_role_cannot_change,
        role_cannot_change_on_incomplete_deployment,
        secrets_require_provider,
        tls_requires_credentials,
        tpu_generation_supported,
        gang_flag_cannot_change,
        tpu_topology_cannot_change,
        gang_pods_need_topology,
        placement_rules_must_parse,
    ]


def _takes_context(validator) -> bool:
    try:
        return len(inspect.signature(validator).parameters) >= 3
    except (TypeError, ValueError):
        return False


def validate_spec_change(
    old: Optional[ServiceSpec],
    new: ServiceSpec,
    validators: Optional[List[Validator]] = None,
    context: Optional[ValidationContext] = None,
) -> None:
    """Run all validators; raise ConfigValidationError on any failure.

    Reference: DefaultConfigurationUpdater.updateConfiguration flow —
    validation errors keep the previous target config active.  A
    validator that RAISES (instead of returning errors) must not let
    a candidate config slip past the other 18 checks or crash the
    update endpoint: a raised ConfigValidationError's entries are
    folded in, and any other exception becomes a validation error
    naming the broken validator (the config is rejected, the operator
    sees which check to fix).
    """
    errors: List[str] = []
    for validator in validators if validators is not None else default_validators():
        try:
            if _takes_context(validator):
                errors.extend(validator(old, new, context))
            else:
                errors.extend(validator(old, new))
        except ConfigValidationError as e:
            errors.extend(e.errors)
        except Exception as e:
            name = getattr(validator, "__name__", repr(validator))
            errors.append(f"validator {name} crashed: {e!r}")
    if errors:
        raise ConfigValidationError(errors)
