"""EventJournal: a durable, capacity-bounded fleet event log.

Traceview's flight recorder answers "what spans did the last N cycles
record" — in-memory, drop-oldest, gone on restart.  The journal is the
complementary surface: a small, append-only record of the events an
operator asks about AFTER the fact — operator verbs, plan/phase
transitions, failovers and lease epochs, admission rejections,
recovery actions, and detector alerts — persisted as ONE property in
the scheduler's state store, so in HA mode it rides the lease-fenced
writer and replays to the successor after a failover (the deposed
leader's post-deposition flush is rejected by the fence and counted,
never raced in).

Capacity-bounded by construction (drop-oldest at ``capacity``
events); the sequence number is monotonic ACROSS incarnations — a
successor resumes at ``seq+1``, so ``GET /v1/debug/events?since=``
cursors held by an operator survive a failover.

Writes are batched: ``append()`` is an in-memory deque push; the
owning loop calls ``flush()`` once per cycle (and the HTTP layer
flushes after operator verbs, which deserve immediate durability).  A
store outage degrades the journal, never the scheduler: flush errors
are swallowed and counted in ``write_errors``.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from dcos_commons_tpu.storage.persister import PersisterError

JOURNAL_PROPERTY = "health-journal"
JOURNAL_PATH = "/__health__/journal"
DEFAULT_CAPACITY = 512


class StatePropertyBackend:
    """Persist the journal as a state-store property (per-service
    namespacing and HA fencing come from the store's wired persister)."""

    def __init__(self, state_store, key: str = JOURNAL_PROPERTY):
        self._state_store = state_store
        self._key = key

    def load(self) -> Optional[bytes]:
        return self._state_store.fetch_property(self._key)

    def store(self, raw: bytes) -> None:
        self._state_store.store_property(self._key, raw)


class PersisterBackend:
    """Persist the journal at a raw tree path — the multi scheduler's
    fleet-level journal (admission rejections target services that may
    not exist yet, so no service store can own them)."""

    def __init__(self, persister, path: str = JOURNAL_PATH):
        self._persister = persister
        self._path = path

    def load(self) -> Optional[bytes]:
        return self._persister.get_or_none(self._path)

    def store(self, raw: bytes) -> None:
        # durcheck: dur-unfenced-write=builder injects a FencedPersister in HA mode, so the fence lives in the instance, not this call site
        self._persister.set(self._path, raw)


class EventJournal:
    """Append/query/flush; thread-safe (HTTP verbs append from server
    threads while the cycle thread flushes)."""

    def __init__(self, backend=None, capacity: int = DEFAULT_CAPACITY):
        self._backend = backend
        # capacity 0 = the DISABLED journal (health plane off): every
        # surface stays callable, nothing is recorded or persisted
        self.capacity = max(0, int(capacity))
        self._events: deque = deque(maxlen=self.capacity or 1)
        self._seq = 0
        self._dirty = False
        self._loaded = backend is None or not self.capacity
        self.write_errors = 0
        self._lock = threading.Lock()
        # serializes snapshot+store as one unit: two racing flushes
        # (the cycle's throttled flush vs an operator verb's inline
        # flush) must persist in snapshot order, or the earlier
        # payload can land LAST and a crash-then-replay would lose the
        # newer events and re-mint their seqs.  Separate from _lock so
        # append() never blocks on store IO.
        self._flush_lock = threading.Lock()

    @property
    def enabled(self) -> bool:
        return self.capacity > 0

    # -- persistence --------------------------------------------------

    def _load_locked(self) -> None:
        if self._loaded:
            return
        self._loaded = True
        try:
            raw = self._backend.load()
        except PersisterError:
            # unreadable store at build time: start empty; the next
            # flush will overwrite (or fail and be counted)
            return
        if raw is None:
            return
        try:
            data = json.loads(raw.decode("utf-8"))
            events = data.get("events") or []
            seq = int(data.get("seq", 0))
        except (ValueError, TypeError, UnicodeDecodeError):
            return  # corrupt journal must not brick the scheduler
        for event in events:
            if isinstance(event, dict):
                self._events.append(event)
        # the persisted seq dominates the replayed tail (events may
        # have been dropped by the capacity bound before the save)
        self._seq = max(
            seq, max((e.get("seq", 0) for e in self._events), default=0)
        )

    def load(self) -> None:
        with self._lock:
            self._load_locked()

    def flush(self) -> bool:
        """Persist if dirty.  Returns True when a write happened.
        Store failures (including a deposed leader's fenced write) are
        swallowed and counted — the journal is telemetry, and the
        fence's own rejection counter tells the real story."""
        with self._flush_lock:
            with self._lock:
                if not self._dirty or self._backend is None:
                    return False
                payload = json.dumps({
                    "seq": self._seq,
                    "events": list(self._events),
                }, sort_keys=True).encode("utf-8")
                self._dirty = False
            try:
                self._backend.store(payload)
                return True
            except PersisterError:
                with self._lock:
                    self._dirty = True
                    self.write_errors += 1
                return False

    # -- append / query -----------------------------------------------

    def append(
        self, kind: str, message: str = "", t: Optional[float] = None,
        **attrs,
    ) -> dict:
        """Record one event; returns it (with its assigned seq)."""
        if not self.capacity:
            return {}
        event: Dict[str, object] = {
            "kind": str(kind),
            "t": round(time.time() if t is None else t, 3),
        }
        if message:
            event["message"] = str(message)
        for key, value in attrs.items():
            if value is None:
                continue
            event[key] = value if isinstance(
                value, (int, float, bool)
            ) else str(value)
        with self._lock:
            self._load_locked()
            self._seq += 1
            event["seq"] = self._seq
            self._events.append(event)
            self._dirty = True
        return event

    def events(
        self, since: int = 0, kinds=None, limit: int = 0
    ) -> List[dict]:
        """Events with seq > ``since``, oldest first; optionally
        filtered by kind and capped to the newest ``limit``."""
        with self._lock:
            self._load_locked()
            out = [e for e in self._events if e.get("seq", 0) > since]
        if kinds:
            kinds = set(kinds)
            out = [e for e in out if e.get("kind") in kinds]
        if limit and len(out) > limit:
            out = out[-limit:]
        return out

    @property
    def last_seq(self) -> int:
        with self._lock:
            self._load_locked()
            return self._seq

    def describe(self) -> dict:
        with self._lock:
            self._load_locked()
            return {
                "seq": self._seq,
                "events": len(self._events),
                "capacity": self.capacity,
                "write_errors": self.write_errors,
            }
