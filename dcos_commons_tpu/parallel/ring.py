"""Ring attention: context parallelism over the ``sp`` mesh axis.

Long-context first-class requirement (task brief + SURVEY.md section
5.7 green-field note): each device holds one contiguous sequence chunk
of Q/K/V; K/V chunks rotate around the ring via ppermute while every
device accumulates flash-style online-softmax partial results.  With
the scheduler's torus placement (offer/torus.py) ring neighbors are
ICI-adjacent, so each hop is one ICI transfer overlapped with the
block attention compute.

Numerics: accumulation in float32 with a finite mask sentinel; output
cast back to the input dtype.  Causality is enforced across chunks by
comparing global positions (chunk_index * chunk_len + offset).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from dcos_commons_tpu.parallel.compat import axis_size as _mesh_axis_size

_NEG = -1e30


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str = "sp",
    causal: bool = True,
    axis_size: Optional[int] = None,
) -> jax.Array:
    """Attention over a sequence sharded on ``axis_name``.

    Must run inside shard_map/pjit with ``axis_name`` bound.  Shapes
    (per device): q/k/v [batch, heads, chunk, head_dim].
    """
    if axis_size is None:
        axis_size = _mesh_axis_size(axis_name)
    chunk = q.shape[-2]
    scale = q.shape[-1] ** -0.5
    my_idx = lax.axis_index(axis_name)

    q32 = q.astype(jnp.float32) * scale
    # accumulators start as constants but become device-varying inside
    # the loop; mark them varying up front for shard_map's vma checker
    def _vary(x):
        from dcos_commons_tpu.parallel.compat import pvary

        return pvary(x, (axis_name,))

    o = _vary(jnp.zeros(q.shape[:-1] + (v.shape[-1],), jnp.float32))
    m = _vary(jnp.full(q.shape[:-1], _NEG, jnp.float32))
    l = _vary(jnp.zeros(q.shape[:-1], jnp.float32))

    q_pos = my_idx * chunk + lax.broadcasted_iota(jnp.int32, (chunk, chunk), 0)
    k_off = lax.broadcasted_iota(jnp.int32, (chunk, chunk), 1)
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    def step(i, carry):
        o, m, l, k_cur, v_cur = carry
        src = (my_idx - i) % axis_size  # chunk index currently held
        s = jnp.einsum(
            "bhqd,bhkd->bhqk", q32, k_cur.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        if causal:
            valid = q_pos >= (src * chunk + k_off)
            s = jnp.where(valid, s, _NEG)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        if causal:
            p = jnp.where(valid, p, 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1)
        o_new = o * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_cur.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        # rotate K/V to the next ring position; the final rotation
        # restores the original owner (a free no-op in steady state)
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return o_new, m_new, l_new, k_next, v_next

    o, m, l, _, _ = lax.fori_loop(0, axis_size, step, (o, m, l, k, v))
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def reference_attention(q, k, v, causal: bool = True) -> jax.Array:
    """Dense single-device attention — the numerics oracle for tests."""
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum(
        "bhqd,bhkd->bhqk",
        q.astype(jnp.float32) * scale,
        k.astype(jnp.float32),
    )
    if causal:
        qn, kn = s.shape[-2], s.shape[-1]
        mask = lax.broadcasted_iota(jnp.int32, (qn, kn), 0) >= \
            lax.broadcasted_iota(jnp.int32, (qn, kn), 1)
        s = jnp.where(mask, s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(jnp.float32)
    ).astype(q.dtype)
