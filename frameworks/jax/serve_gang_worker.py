"""Sharded serving gang worker: continuous batching over a multi-host
jax.distributed gang, fronted by rank 0's HTTP server.

The serving half of the flagship at GANG scale: the model's parameters
are tensor-parallel-sharded across every chip of the gang (a model too
big for one host serves from the whole slice), and the slot-pool KV
cache (dcos_commons_tpu/serve/) is laid over the same mesh.  SPMD
serving needs every process in every collective, but requests arrive
only at the VIP'd rank — so rank 0 drives the gang with PER-TICK
broadcast ops and every rank executes the identical payload:

    NOOP    keep the gang meeting in a collective while idle
    ADMIT   prefill ONE request (paged: ONE CHUNK of one request's
            prompt, through its page table) into the pool
    DECODE  advance EVERY pool row one step (per-row pos/temp/seed;
            paged: through per-row page tables)

Requests therefore join and leave MID-FLIGHT: a request arriving
while others decode is admitted at the next tick (TTFT = one tick +
its own prefill, not a whole preceding generation), and a row hitting
its EOS/max-token retires its slot immediately while the rest keep
stepping.  The driver/follower shape is unchanged from the
dispatch-per-group protocol this replaces (spmdcheck-clean: followers
just execute the broadcast payload), only the op vocabulary grew.

PAGED KV (ISSUE 11, the default — KV_PAGE_TOKENS=0 selects the
legacy slot pool): the broadcast payload grows the chunk/page
fields — ADMIT carries a PREFILL_CHUNK_TOKENS-wide prompt chunk, its
traced start position/true length, and the request's page table;
DECODE carries every row's page table alongside its (token, position,
temp, seed) state.  Page allocation, budgeting and the prefix cache
are rank 0's HOST-side bookkeeping (serve/paging.py): followers only
ever see physical page ids in the broadcast tables, so every rank
still executes the identical tick and the collective schedules never
diverge.

Failover comes from GANG recovery, not from this file: kill any host
and the scheduler replaces the whole gang (tests/test_gang_serve.py
semantics); the replacement re-rendezvouses, rebuilds the identical
tp-sharded params, and greedy replies are token-identical
(tests/test_gang_serve_sharded.py proves it end to end).

Reference: the reference never serves models — its analogue is any
multi-task service behind a VIP (sdk/scheduler
offer/evaluate/PodInfoBuilder VIP labels); the gang/SPMD shape is the
TPU-first addition.
"""

import json
import math
import os
import sys

import numpy as np
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

sys.path.insert(0, os.environ.get("REPO_ROOT", "/root/repo"))

from dcos_commons_tpu.serve import (  # noqa: E402
    SERVESTATS_NAME,
    PagedEngine,
    SlotEngine,
    paged_config_from_env,
)
from dcos_commons_tpu.trace.steplog import StepLog  # noqa: E402
from dcos_commons_tpu.utils.microbatch import QueueTimeoutError  # noqa: E402

# how often idle ranks meet in a noop collective: the gang must stay
# in lockstep even with no traffic, or a request would wait on ranks
# parked in a stale program
IDLE_TICK_S = 0.05

# per-tick broadcast ops (the old one-shot OP_GENERATE grew into the
# ADMIT/DECODE pair so requests join and leave mid-flight)
OP_NOOP = 0
OP_ADMIT = 1
OP_DECODE = 2

# steplog sampling: continuous batching ticks once per TOKEN, not per
# request — record the first few ticks then every Nth so the skew
# signal survives without an unbounded file
_STEPLOG_EVERY = 64


def _zero_payload(slots, prompt_len):
    return (
        np.zeros(6, np.int64),                # head [op, a, b, c, d, e]
        np.zeros((slots, 4), np.int64),       # rows [tok, pos, temp_u, seed]
        np.zeros((1, prompt_len), np.int32),  # ADMIT prompt
    )


def _broadcast_tick(multihost_utils, payload, slots, prompt_len):
    """One gang-wide broadcast: rank 0 passes (head, rows, prompt),
    the followers pass None and receive rank 0's payload.  Every
    tick's payload has the same byte shape regardless of op, so the
    broadcast cost is flat and the follower loop is shape-stable.

    head by op: ADMIT = [op, slot, true_len, seed, temp_micro, 0];
    DECODE = [op, n_active, 0, 0, 0, 0]; NOOP = zeros.  ``rows``
    carries the DECODE pool state (token, position, temperature in
    micro-units, per-row PRNG seed)."""
    if payload is None:
        payload = _zero_payload(slots, prompt_len)
    head, rows, prompt = multihost_utils.broadcast_one_to_all(payload)
    return np.asarray(head), np.asarray(rows), np.asarray(prompt)


def _execute_tick(pool, head, rows, prompt):
    """Run the broadcast op — EVERY rank (driver included) executes
    the identical payload, so traced operands are byte-identical
    across the gang and the collective schedules never diverge.
    Returns the op's result (first token for ADMIT, next-token vector
    for DECODE, None for NOOP)."""
    op = int(head[0])
    if op == OP_ADMIT:
        return pool.prefill(
            prompt, slot=int(head[1]), true_len=int(head[2]),
            temp=int(head[4]) / 1e6, seed=int(head[3]),
        )
    if op == OP_DECODE:
        return pool.decode(
            rows[:, 0].astype(np.int32),
            rows[:, 1].astype(np.int32),
            (rows[:, 2] / 1e6).astype(np.float32),
            rows[:, 3].astype(np.int32),
        )
    return None


# -- paged protocol (ISSUE 11) ----------------------------------------
# the legacy payload grew chunk/page fields: ADMIT is now one prompt
# CHUNK through the request's page table, DECODE rides every row's
# table.  Same flat-byte-shape discipline: every tick broadcasts the
# same tuple of arrays regardless of op.


def _zero_paged_payload(slots, pages_per_row, chunk_tokens):
    return (
        # head by op: ADMIT = [op, slot, start, true_len, seed,
        # temp_micro]; DECODE = [op, n_active, 0, 0, 0, 0]; NOOP = 0s
        np.zeros(6, np.int64),
        np.zeros((slots, 4), np.int64),   # rows [tok, pos, temp_u, seed]
        np.zeros((slots, pages_per_row), np.int64),  # page tables
        np.zeros((1, chunk_tokens), np.int32),       # ADMIT chunk
    )


def _broadcast_paged_tick(multihost_utils, payload, slots,
                          pages_per_row, chunk_tokens):
    """One gang-wide broadcast of the paged payload: rank 0 passes
    (head, rows, tables, chunk), followers pass None and receive rank
    0's.  Flat cost per tick: the byte shape never depends on op."""
    if payload is None:
        payload = _zero_paged_payload(slots, pages_per_row, chunk_tokens)
    head, rows, tables, chunk = multihost_utils.broadcast_one_to_all(
        payload
    )
    return (
        np.asarray(head), np.asarray(rows), np.asarray(tables),
        np.asarray(chunk),
    )


def _execute_paged_tick(pool, head, rows, tables, chunk):
    """Execute the broadcast paged op on EVERY rank (rank 0 included)
    — page ids arrive as data, so the traced operands are
    byte-identical across the gang."""
    op = int(head[0])
    if op == OP_ADMIT:
        slot = int(head[1])
        return pool.prefill_chunk(
            chunk, slot=slot, table=tables[slot].astype(np.int32),
            start=int(head[2]), true_len=int(head[3]),
            temp=int(head[5]) / 1e6, seed=int(head[4]),
        )
    if op == OP_DECODE:
        return pool.decode(
            rows[:, 0].astype(np.int32),
            rows[:, 1].astype(np.int32),
            (rows[:, 2] / 1e6).astype(np.float32),
            rows[:, 3].astype(np.int32),
            tables.astype(np.int32),
        )
    return None


def main() -> int:
    from dcos_commons_tpu.parallel.distributed import initialize_from_env

    contract = initialize_from_env()

    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax.experimental import multihost_utils
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from dcos_commons_tpu.metrics.registry import Metrics
    from dcos_commons_tpu.models import config_from_env, init_params
    from dcos_commons_tpu.models.transformer import param_shardings
    from dcos_commons_tpu.parallel.mesh import MeshSpec, make_mesh
    from dcos_commons_tpu.serve.pool import PagedPoolModel, PoolModel
    from dcos_commons_tpu.utils import (
        enable_compilation_cache,
        restore_checkpoint,
    )

    enable_compilation_cache()
    rank = contract["worker_id"]
    # a RELAUNCH reuses the sandbox: a stale ready file from the
    # previous incarnation must not pass readiness while we are cold
    try:
        os.remove("ready")
    except OSError:
        pass
    config = config_from_env(
        os.environ,
        dtype=jnp.bfloat16 if os.environ.get(
            "JAX_PLATFORMS"
        ) != "cpu" else jnp.float32,
        remat=False,
    )
    max_len = int(os.environ.get("MAX_LEN", "256"))
    # unset SERVE_BATCH means a bare/dev launch; fall back to one
    # request rather than the deploy default 8 (see options.json
    # serving.batch description)
    # sdklint: disable=config-default-drift — dev fallback
    batch = int(os.environ.get("SERVE_BATCH", "1"))
    # "" and 0 both mean "use SERVE_BATCH" (the options.json default)
    slots = int(os.environ.get("SERVE_SLOTS") or 0) or batch
    new_tokens = int(os.environ.get("MAX_NEW_TOKENS", "32"))
    prompt_len = max_len - new_tokens

    # the WHOLE gang is one tp axis: the model lives sharded across
    # every chip (ICI within hosts, DCN across under a dcn axis would
    # slot in here for multi-slice; the test gang is one slice)
    n_devices = len(jax.devices())
    mesh = make_mesh(MeshSpec(tp=n_devices))
    with mesh:
        params = init_params(config, jax.random.key(0))
        ckpt_dir = os.environ.get("CHECKPOINT_DIR", "")
        if ckpt_dir:
            state, step = restore_checkpoint(ckpt_dir, {"params": params})
            if step is not None:
                params = state["params"]
                print(f"restored checkpoint step {step}", flush=True)
        params = jax.tree.map(
            jax.device_put, params, param_shardings(config, mesh)
        )
        if os.environ.get("WEIGHT_DTYPE", "native") == "int8":
            # quantize AFTER placement: GSPMD derives the int8/scale
            # shardings from the already-sharded weights, so the
            # {"q","scale"} leaves need no new sharding rules
            from dcos_commons_tpu.models import quantize_params_int8

            params = jax.jit(quantize_params_int8)(params)
            if rank == 0:
                print("weights quantized to int8 (per-channel)", flush=True)
        replicated = NamedSharding(mesh, P())

        def to_global(arr):
            """Identical host-local array on every rank -> one global
            replicated jax array the sharded pool accepts."""
            return multihost_utils.host_local_array_to_global_array(
                arr, mesh, P()
            )

        kv_dtype = os.environ.get("KV_DTYPE", "native")
        # the pool's KV heads ride the tp axis like the attention
        # weights when they divide it; otherwise the cache replicates
        # (tiny-head test configs on wide meshes).  The paged arena
        # keeps kv heads on dim 3 — (layers, pages, page_tokens, kv,
        # hd) — so the SAME spec lays both pools
        kv_spec = (
            P(None, None, None, "tp", None)
            if config.n_kv_heads % n_devices == 0 else P()
        )
        paged = paged_config_from_env(os.environ)
        if paged is not None:
            pool = PagedPoolModel(
                config, params, slots, max_len, paged.page_tokens,
                paged.pages, paged.chunk_tokens, kv_dtype=kv_dtype,
                cache_sharding=NamedSharding(mesh, kv_spec),
                put=to_global,
                constrain_out=lambda x: (
                    jax.lax.with_sharding_constraint(x, replicated)
                ),
            )
        else:
            pool = PoolModel(
                config, params, slots, max_len, kv_dtype=kv_dtype,
                cache_sharding=NamedSharding(mesh, kv_spec),
                put=to_global,
                constrain_out=lambda x: (
                    jax.lax.with_sharding_constraint(x, replicated)
                ),
            )

        # warm the compiled pool as a GANG before readiness: the first
        # request must not pay the compiles, and a rank that cannot
        # compile must fail deploy, not the first client.  Every rank
        # reaches this call at the same program point (pre-loop).
        if paged is not None:
            pool.warm()
        else:
            pool.warm(prompt_len)
        pages_per_row = paged.pages_per_row if paged is not None else 0
        chunk_tokens = paged.chunk_tokens if paged is not None else 0

        # per-tick step telemetry ($SANDBOX/steplog.jsonl): sampled
        # decode ticks on every rank — wall seconds, active rows, and
        # for followers the time spent parked in the broadcast waiting
        # for rank 0 (the serving gang's skew/idle signal).  Surfaced
        # by the scheduler's /v1/debug/trace as one lane per host.
        import time as _time

        steplog = StepLog()
        tick_count = [0]

        def _log_tick(wall_s, blocked_s, active):
            n = tick_count[0]
            tick_count[0] += 1
            if n >= 4 and n % _STEPLOG_EVERY:
                return
            steplog.record(
                n,
                wall_s=round(wall_s, 6),
                blocked_s=round(blocked_s, 6),
                rows=active,
                tokens=active,
                worker=rank,
            )

        # Intentional driver/follower split: BOTH sides of this branch
        # run the identical collective sequence (one _broadcast_tick
        # per tick; _execute_tick runs the same op payload on every
        # rank), so the schedules never diverge; the branch only
        # decides who PRODUCES the payload that every rank consumes.
        # sdklint: disable=spmd-host-branch — driver loops meet in the broadcast
        if rank != 0:
            # follower loop: meet rank 0 in every broadcast tick and
            # execute whatever it scheduled
            with open("ready", "w") as f:
                f.write("warm\n")
            print(f"rank {rank}: following gang broadcasts", flush=True)
            if paged is not None:
                while True:
                    b0 = _time.time()
                    head, rows, tables, chunk = _broadcast_paged_tick(
                        multihost_utils, None, slots, pages_per_row,
                        chunk_tokens,
                    )
                    blocked_s = _time.time() - b0
                    t0 = _time.time()
                    _execute_paged_tick(pool, head, rows, tables, chunk)
                    if int(head[0]) == OP_DECODE:
                        _log_tick(
                            _time.time() - t0, blocked_s, int(head[1])
                        )
            while True:
                b0 = _time.time()
                head, rows, prompt = _broadcast_tick(
                    multihost_utils, None, slots, prompt_len
                )
                blocked_s = _time.time() - b0
                t0 = _time.time()
                _execute_tick(pool, head, rows, prompt)
                if int(head[0]) == OP_DECODE:
                    _log_tick(_time.time() - t0, blocked_s, int(head[1]))

        # ---- rank 0: HTTP front end + the slot engine ---------------
        # engine callbacks broadcast the op, then execute it exactly
        # like a follower would (one code path = no divergence);
        # on_idle keeps the followers meeting in noop collectives.
        def prefill_fn(padded, slot, true_len, temp, seed):
            # round() like decode_fn does: truncation would give a
            # request's FIRST token a different temperature than its
            # later tokens (0.07*1e6 truncates to 69999)
            head = np.asarray(
                [OP_ADMIT, slot, true_len, seed, round(temp * 1e6), 0],
                np.int64,
            )
            _, zero_rows, _ = _zero_payload(slots, prompt_len)
            head, rows, prompt = _broadcast_tick(
                multihost_utils,
                (head, zero_rows, padded.astype(np.int32)),
                slots, prompt_len,
            )
            return _execute_tick(pool, head, rows, prompt)

        def decode_fn(tok, pos, temps, seeds, n_active):
            head = np.asarray(
                [OP_DECODE, n_active, 0, 0, 0, 0], np.int64
            )
            rows = np.stack([
                tok.astype(np.int64),
                pos.astype(np.int64),
                np.round(temps.astype(np.float64) * 1e6).astype(np.int64),
                seeds.astype(np.int64),
            ], axis=1)
            zero_prompt = np.zeros((1, prompt_len), np.int32)
            head, rows, prompt = _broadcast_tick(
                multihost_utils, (head, rows, zero_prompt),
                slots, prompt_len,
            )
            t0 = _time.time()
            out = _execute_tick(pool, head, rows, prompt)
            # rank 0 paces the gang; it never waits in the broadcast
            _log_tick(_time.time() - t0, 0.0, n_active)
            return out

        def idle_tick():
            _broadcast_tick(multihost_utils, None, slots, prompt_len)

        # -- paged protocol callbacks (ISSUE 11): same shape, the
        # payload carries chunk/page fields and every rank executes
        # the identical _execute_paged_tick
        def paged_prefill_fn(padded, slot, table, start, true_len,
                             temp, seed):
            head = np.asarray(
                [OP_ADMIT, slot, start, true_len, seed,
                 round(temp * 1e6)],
                np.int64,
            )
            _, zero_rows, zero_tables, _ = _zero_paged_payload(
                slots, pages_per_row, chunk_tokens
            )
            zero_tables[slot] = table
            out = _broadcast_paged_tick(
                multihost_utils,
                (head, zero_rows, zero_tables,
                 padded.astype(np.int32)),
                slots, pages_per_row, chunk_tokens,
            )
            return _execute_paged_tick(pool, *out)

        def paged_decode_fn(tok, pos, temps, seeds, tables, n_active):
            head = np.asarray(
                [OP_DECODE, n_active, 0, 0, 0, 0], np.int64
            )
            rows = np.stack([
                tok.astype(np.int64),
                pos.astype(np.int64),
                np.round(
                    temps.astype(np.float64) * 1e6
                ).astype(np.int64),
                seeds.astype(np.int64),
            ], axis=1)
            zero_chunk = np.zeros((1, chunk_tokens), np.int32)
            bcast = _broadcast_paged_tick(
                multihost_utils,
                (head, rows, tables.astype(np.int64), zero_chunk),
                slots, pages_per_row, chunk_tokens,
            )
            t0 = _time.time()
            out = _execute_paged_tick(pool, *bcast)
            # rank 0 paces the gang; it never waits in the broadcast
            _log_tick(_time.time() - t0, 0.0, n_active)
            return out

        def paged_idle_tick():
            _broadcast_paged_tick(
                multihost_utils, None, slots, pages_per_row,
                chunk_tokens,
            )

        queue_timeout_s = float(
            os.environ.get("SERVE_QUEUE_TIMEOUT_S", "600")
        )
        metrics = Metrics()
        stats_path = os.path.join(
            os.environ.get("SANDBOX", "."), SERVESTATS_NAME
        )

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def do_GET(self):
                if self.path.split("?")[0] != "/stats":
                    self.send_error(404)
                    return
                payload = json.dumps(engine.stats()).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_POST(self):
                if self.path != "/generate":
                    self.send_error(404)
                    return
                length = int(self.headers.get("Content-Length", 0))
                try:
                    body = json.loads(self.rfile.read(length))
                    rows = body["tokens"]
                    if len(rows) > batch:
                        raise ValueError(
                            f"{len(rows)} prompts > server batch {batch}"
                        )
                    # rows may have MIXED lengths: each rides its own
                    # pool slot with its own true_len
                    for row in rows:
                        if not 1 <= len(row) <= prompt_len:
                            raise ValueError(
                                f"prompt length must be in "
                                f"[1, {prompt_len}]"
                            )
                    if not rows:
                        raise ValueError("tokens must be non-empty")
                    temp = float(body.get("temperature", 0.0))
                    if not math.isfinite(temp) or not 0.0 <= temp <= 1e4:
                        # bounded: the broadcast head carries the value
                        # as micro-units in an int64 — and a six-digit
                        # temperature is an input error anyway
                        raise ValueError(
                            f"temperature must be in [0, 10000], got {temp}"
                        )
                    n = min(
                        int(body.get("max_new_tokens", new_tokens)),
                        new_tokens,
                    )
                    if n < 1:
                        raise ValueError("max_new_tokens must be >= 1")
                    eos = body.get("eos")
                    if eos is not None:
                        eos = int(eos)
                        if not 0 <= eos < config.vocab:
                            raise ValueError(
                                f"eos must be in [0, {config.vocab})"
                            )
                    result = engine.submit(
                        [[int(t) % config.vocab for t in row]
                         for row in rows],
                        n, temperature=temp, eos_id=eos,
                    )
                    payload = json.dumps({"tokens": result}).encode()
                    self.send_response(200)
                except QueueTimeoutError as e:
                    # saturation, NOT caller error: no KV slot freed
                    # in time — clients/load generators back off
                    payload = json.dumps({"error": str(e)}).encode()
                    self.send_response(503)
                except Exception as e:  # noqa: BLE001
                    payload = json.dumps({"error": str(e)}).encode()
                    self.send_response(400)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

        # bind BEFORE building the engine: the actually-bound port
        # rides the engine's first stats flush (the /v1/endpoints
        # `advertise: true` contract); on a shared machine a taken
        # assigned port falls back to an ephemeral bind + advertise
        port = int(os.environ.get("PORT_HTTP", "0"))
        try:
            server = ThreadingHTTPServer(("0.0.0.0", port), Handler)
        except OSError:
            server = ThreadingHTTPServer(("0.0.0.0", 0), Handler)
            print(
                f"rank 0: port {port} in use; bound "
                f"{server.server_address[1]} instead (advertised via "
                "servestats)",
                flush=True,
            )
        bound_port = int(server.server_address[1])
        if paged is not None:
            engine = PagedEngine(
                paged_prefill_fn, paged_decode_fn, slots, max_len,
                prompt_len,
                page_tokens=paged.page_tokens, pages=paged.pages,
                chunk_tokens=paged.chunk_tokens,
                prefix_cache=paged.prefix_cache,
                queue_timeout_s=queue_timeout_s,
                on_idle=paged_idle_tick, idle_every_s=IDLE_TICK_S,
                stats_path=stats_path,
                log=lambda msg: print(msg, flush=True),
                extra_stats={"http_port": bound_port},
            )
        else:
            engine = SlotEngine(
                prefill_fn, decode_fn, slots, max_len, prompt_len,
                queue_timeout_s=queue_timeout_s,
                on_idle=idle_tick, idle_every_s=IDLE_TICK_S,
                stats_path=stats_path,
                log=lambda msg: print(msg, flush=True),
                extra_stats={"http_port": bound_port},
            )
        engine.register_metrics(metrics)
        with open("ready", "w") as f:
            f.write("warm\n")
        shape = (
            f"{paged.pages}-page arena (pages of {paged.page_tokens}, "
            f"{slots} rows, chunk {paged.chunk_tokens})"
            if paged is not None else f"{slots}-slot pool"
        )
        print(
            f"rank 0: serving sharded generate over a {shape} "
            f"(prompts<={prompt_len}->{new_tokens}) tp={n_devices} "
            f"on {server.server_address[1]}",
            flush=True,
        )
        server.serve_forever()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
