"""TraceRecorder: a bounded in-memory flight recorder for spans.

The recorder is the one shared object of the tracing subsystem: spans
are minted here (``span()``/``event()``), finished spans land in a
thread-safe fixed-capacity ring buffer (drop-oldest — the recorder is
a FLIGHT recorder, not an archive), and the launch registry maps task
ids to the launch span that created them so a status arriving many
cycles later still joins its launch's correlation chain.

Overhead is bounded by design: a span is one small object + one
deque append under a leaf lock; a disabled recorder (``capacity=0``)
hands out a shared no-op span, so ``bench_trace_overhead`` can fence
the enabled-vs-disabled delta (<5% of the offer-cycle figure).
Ring overflow is observable: every evicted span increments the
``trace.dropped`` Metrics counter and the recorder's ``dropped``
count, which the exporters surface.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from typing import List, NamedTuple, Optional

from dcos_commons_tpu.trace.span import NullSpan, Span, new_id

DEFAULT_CAPACITY = 2048
# launch registry bound: old entries fall off; a status for a launch
# evicted here degrades to an uncorrelated event, never an error
LAUNCH_REGISTRY_CAP = 4096


class LaunchRef(NamedTuple):
    """Where a task id's launch lives in the trace."""

    trace_id: int
    span_id: int
    track: str


class TraceRecorder:
    def __init__(
        self,
        capacity: int = DEFAULT_CAPACITY,
        metrics=None,
        service: str = "",
    ):
        self.capacity = max(0, int(capacity))
        self.metrics = metrics
        self.service = service
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.capacity or 1)
        self._dropped = 0
        self._launches: "OrderedDict[str, LaunchRef]" = OrderedDict()
        self._null = NullSpan()
        # wall/monotonic anchor pair: spans stamp time.monotonic()
        # (immune to clock steps); exporters add the offset back to
        # align with wall-clock sources like worker steplogs
        self.t0_wall = time.time()
        self.t0_mono = time.monotonic()

    @property
    def enabled(self) -> bool:
        return self.capacity > 0

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def wall_of(self, monotonic_s: float) -> float:
        """Convert a span stamp to wall seconds for export alignment."""
        return self.t0_wall + (monotonic_s - self.t0_mono)

    # -- minting ------------------------------------------------------

    def new_trace_id(self) -> int:
        return new_id()

    def span(
        self,
        name: str,
        parent: Optional[Span] = None,
        trace_id: int = 0,
        parent_id: int = 0,
        track: str = "",
        **attrs,
    ) -> Span:
        """Open a span.  ``parent`` (explicit, never ambient) supplies
        the trace id and parent span id; ``trace_id``/``parent_id``
        override it for cross-cycle correlation (status -> launch).
        The returned span MUST be closed via ``with`` or ``end()``."""
        if not self.enabled:
            return self._null
        if parent is not None and parent is not self._null:
            trace_id = trace_id or parent.trace_id
            parent_id = parent_id or parent.span_id
            track = track or parent.track
        return Span(
            name,
            trace_id=trace_id or self.new_trace_id(),
            parent_id=parent_id,
            track=track,
            attrs=attrs,  # stringified lazily at export (str_attrs)
            recorder=self,
        )

    def event(
        self,
        name: str,
        parent: Optional[Span] = None,
        trace_id: int = 0,
        parent_id: int = 0,
        track: str = "",
        **attrs,
    ) -> Span:
        """An instantaneous span (status arrival, step transition):
        opened and closed in one call, so it can never leak."""
        span = self.span(
            name, parent=parent, trace_id=trace_id, parent_id=parent_id,
            track=track, **attrs,
        )
        span.end()
        return span

    # -- the ring -----------------------------------------------------

    def _record(self, span: Span) -> None:
        overflowed = False
        with self._lock:
            if self.capacity and len(self._ring) >= self.capacity:
                self._dropped += 1
                overflowed = True
            self._ring.append(span)
        if overflowed and self.metrics is not None:
            self.metrics.incr("trace.dropped")

    def snapshot(self) -> List[Span]:
        """Recorded spans, oldest first (a copy; spans are settled —
        only finished spans enter the ring)."""
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._dropped = 0

    # -- launch registry ----------------------------------------------

    def register_launch(
        self, task_id: str, span: Span, track: str = ""
    ) -> None:
        """Remember which launch span created ``task_id`` so later
        status arrivals (and the plan-step transitions they trigger)
        join the launch's correlation chain."""
        if not self.enabled or span is self._null:
            return
        ref = LaunchRef(span.trace_id, span.span_id, track or span.track)
        with self._lock:
            self._launches[task_id] = ref
            self._launches.move_to_end(task_id)
            while len(self._launches) > LAUNCH_REGISTRY_CAP:
                self._launches.popitem(last=False)

    def launch_ref(self, task_id: str) -> Optional[LaunchRef]:
        with self._lock:
            return self._launches.get(task_id)


# the shared disabled recorder: layers that may be wired without a
# tracer (hand-built evaluators in tests) default to this and stay
# branch-free at every call site
NULL_TRACER = TraceRecorder(capacity=0)
