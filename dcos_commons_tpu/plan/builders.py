"""Plan factories: ServiceSpec + persisted state -> deploy plan.

Reference: plan/DeployPlanFactory.java, DefaultPhaseFactory.java,
DefaultStepFactory.java — the step factory consults the StateStore to
decide each step's initial status: a task already launched at the
target config and at its goal state yields a COMPLETE step, so
scheduler restarts resume plans mid-step (SchedulerRestartServiceTest
is the reference's proof; our test_plan_resume mirrors it).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from dcos_commons_tpu.common import Label
from dcos_commons_tpu.plan.backoff import Backoff
from dcos_commons_tpu.plan.phase import Phase
from dcos_commons_tpu.plan.plan import DEPLOY_PLAN_NAME, Plan
from dcos_commons_tpu.plan.step import DeploymentStep, PodInstanceRequirement
from dcos_commons_tpu.plan.strategy import strategy_for_name
from dcos_commons_tpu.specification.specs import (
    PodSpec,
    ServiceSpec,
    task_full_name,
)
from dcos_commons_tpu.state.state_store import StateStore


def build_instance_steps(
    pod: PodSpec,
    instances: List[int],
    state_store: StateStore,
    target_config_id: str,
    backoff: Optional[Backoff] = None,
) -> List[DeploymentStep]:
    """One deployment step per listed instance of a NON-GANG pod,
    seeded from persisted state exactly like the deploy plan's own
    steps (an already-launched instance restores COMPLETE).  The
    autoscale scale-out phase (health/actions.py) builds its new
    instances through this, so an automated scale-out deploys through
    the identical launch path — and re-synthesizing the phase after a
    failover can never re-deploy what already landed."""
    factory = DeployPlanFactory(backoff)
    return [
        factory._make_step(pod, [index], state_store, target_config_id)
        for index in instances
    ]


class DeployPlanFactory:
    """Builds the default deploy plan: one phase per pod, serial over
    phases; parallel gang pods get one step covering all instances."""

    def __init__(self, backoff: Optional[Backoff] = None):
        self._backoff = backoff

    def build(
        self,
        spec: ServiceSpec,
        state_store: StateStore,
        target_config_id: str,
        plan_name: str = DEPLOY_PLAN_NAME,
    ) -> Plan:
        phases = [
            self.build_phase(pod, state_store, target_config_id)
            for pod in spec.pods
        ]
        return Plan(plan_name, phases, strategy_for_name("serial"))

    def build_phase(
        self,
        pod: PodSpec,
        state_store: StateStore,
        target_config_id: str,
        strategy_name: str = "serial",
        phase_name: str = "",
    ) -> Phase:
        steps: List[DeploymentStep] = []
        if pod.gang:
            # TPU-first: one step = the whole slice (pjit mesh)
            steps.append(
                self._make_step(
                    pod, list(range(pod.count)), state_store, target_config_id
                )
            )
        else:
            for index in range(pod.count):
                steps.append(
                    self._make_step(pod, [index], state_store, target_config_id)
                )
        return Phase(
            phase_name or pod.type, steps, strategy_for_name(strategy_name)
        )

    def _make_step(
        self,
        pod: PodSpec,
        instances: List[int],
        state_store: StateStore,
        target_config_id: str,
    ) -> DeploymentStep:
        requirement = PodInstanceRequirement(pod=pod, instances=instances)
        name = (
            f"{pod.type}-{instances[0]}:[{','.join(requirement.tasks_to_launch)}]"
            if len(instances) == 1
            else f"{pod.type}-gang:[{','.join(requirement.tasks_to_launch)}]"
        )
        step = DeploymentStep(name, requirement, backoff=self._backoff)
        self.seed_step_from_state(step, pod, instances, state_store, target_config_id)
        return step

    def seed_step_from_state(
        self,
        step: DeploymentStep,
        pod: PodSpec,
        instances: List[int],
        state_store: StateStore,
        target_config_id: str,
    ) -> None:
        """Resume semantics: replay persisted launches + statuses into
        the fresh step (reference: DefaultStepFactory.getStatus)."""
        expected: Dict[str, str] = {}
        statuses = []
        missing: List[int] = []
        for index in instances:
            for task_name in step.requirement.tasks_to_launch:
                full = task_full_name(pod.type, index, task_name)
                info = state_store.fetch_task(full)
                if info is None:
                    missing.append(index)
                    break  # never launched for this instance
                if info.labels.get(Label.TARGET_CONFIG) != target_config_id:
                    return  # old config: needs redeploy -> PENDING
                if info.labels.get(Label.PERMANENTLY_FAILED):
                    return  # needs replacement, recovery will claim it
                expected[full] = info.task_id
                status = state_store.fetch_status(full)
                if status is not None:
                    statuses.append(status)
        if missing:
            # A missing clean SUFFIX of an elastic gang whose initial
            # deployment already completed is an elastic shrink's
            # trim-surplus erase (ISSUE 13/20), not an interrupted
            # deploy: seed the surviving prefix as launched so the
            # restart-rebuilt plan re-derives COMPLETE.  The width is
            # the recovery manager's business — its regrow scan
            # (_maybe_regrow) re-places the gang at declared width
            # when capacity returns; a PENDING full-width step here
            # would instead deadlock against the survivors' own
            # reservations while blocking regrow as externally
            # managed.  Any other hole stays PENDING.
            elastic_gang = (
                pod.gang and pod.tpu is not None and pod.tpu.elastic
            )
            suffix = list(range(min(missing), max(instances) + 1))
            is_clean_suffix = missing == suffix and min(missing) > min(
                instances
            )
            if not (
                elastic_gang
                and is_clean_suffix
                and state_store.deployment_was_completed()
            ):
                return  # never launched: step stays PENDING
        # ONCE tasks that already FINISHED must not re-run even though
        # a fresh launch would: mark complete directly
        step.record_launch(expected)
        for status in statuses:
            step.update(status)
