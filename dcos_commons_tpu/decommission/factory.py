"""DecommissionPlanFactory: state-vs-spec diff -> teardown plan.

Reference: scheduler/decommission/DecommissionPlanFactory.java — for
each surplus pod instance, a serial step sequence: mark + kill its
tasks (TriggerDecommissionStep), unreserve its resources
(ResourceCleanupStep analogue over the reservation ledger), erase its
task state (EraseTaskStateStep).  Highest indices decommission first,
so the surviving instances are always the dense prefix 0..count-1.

The plan is re-derived from the state diff on every scheduler
(re)build, which makes each step idempotent — a crash mid-teardown
resumes by recomputing what is still surplus.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from dcos_commons_tpu.common import TaskInfo
from dcos_commons_tpu.plan.phase import Phase
from dcos_commons_tpu.plan.plan import Plan
from dcos_commons_tpu.plan.step import ActionStep
from dcos_commons_tpu.plan.strategy import SerialStrategy
from dcos_commons_tpu.specification.specs import (
    ServiceSpec,
    pod_instance_name,
    task_full_name,
)
from dcos_commons_tpu.state.state_store import StateStore

DECOMMISSION_PLAN_NAME = "decommission"


def find_surplus_instances(
    spec: ServiceSpec, state_store: StateStore
) -> List[Tuple[str, int, List[str]]]:
    """(pod_type, index, task full-names) for every stored pod instance
    the target spec no longer covers, highest indices first."""
    by_instance: Dict[Tuple[str, int], List[TaskInfo]] = {}
    for info in state_store.fetch_tasks():
        by_instance.setdefault((info.pod_type, info.pod_index), []).append(info)
    known_pods = {p.type: p for p in spec.pods}
    surplus = []
    for (pod_type, index), infos in by_instance.items():
        pod = known_pods.get(pod_type)
        if pod is not None and index < pod.count:
            continue
        surplus.append((pod_type, index, sorted(i.name for i in infos)))
    surplus.sort(key=lambda s: (s[0], -s[1]))
    return surplus


class DecommissionPlanFactory:
    def build(
        self, spec: ServiceSpec, state_store: StateStore,
        exclude: Optional[Set[str]] = None,
    ) -> Plan:
        """``exclude`` names pod instances some OTHER plan already
        owns the teardown of — the builder passes the victims of
        journal-latched in-flight scale-in actions, whose
        re-synthesized phases tear down through the router drain
        grace.  Without the exclusion, a failover mid-scale-in races
        this plan's drain-less kill step against the scale-in's
        drain step for the same instance, and the kill wins."""
        # kill grace periods come from the current spec; tasks of a pod
        # type the spec dropped entirely fall back to immediate kill.
        # The map is keyed by FULL task name (pod-index-task): suffix
        # parsing of stored names would mis-key task specs whose names
        # contain dashes.
        known_pods = {p.type: p for p in spec.pods}
        phases = []
        for pod_type, index, task_names in find_surplus_instances(
            spec, state_store
        ):
            if exclude and pod_instance_name(pod_type, index) in exclude:
                continue
            grace_by_full: Dict[str, float] = {}
            pod = known_pods.get(pod_type)
            if pod is not None:
                for task_spec in pod.tasks:
                    full = task_full_name(pod_type, index, task_spec.name)
                    grace_by_full[full] = task_spec.kill_grace_period_s
            phases.append(
                self._build_phase(pod_type, index, task_names, grace_by_full)
            )
        return Plan(DECOMMISSION_PLAN_NAME, phases, SerialStrategy())

    def _build_phase(
        self,
        pod_type: str,
        index: int,
        task_names: List[str],
        grace_by_full: Dict[str, float],
    ) -> Phase:
        instance = pod_instance_name(pod_type, index)
        phase = Phase(
            f"decommission-{instance}",
            instance_teardown_steps(
                pod_type, index, task_names, grace_by_full
            ),
            SerialStrategy(),
        )
        # endpoint assembly consults ACTIVE teardown targets: the
        # router must see draining:true and stop placing BEFORE any
        # kill fires, even while the backend's task and host are
        # still perfectly healthy (ISSUE 15 satellite — previously
        # only host-level drain flipped the rows)
        phase.decommission_targets = {instance}
        return phase


def instance_teardown_steps(
    pod_type: str,
    index: int,
    task_names: List[str],
    grace_by_full: Dict[str, float],
) -> List[ActionStep]:
    """The kill -> unreserve -> erase step triple for one pod
    instance — the decommission choreography, shared by the surplus
    decommission plan above and the autoscale scale-in phase
    (health/actions.py).  Every step is idempotent: a successor
    re-running them against an already-clean world completes them
    immediately."""
    instance = pod_instance_name(pod_type, index)
    asset = {instance}

    def kill_tasks(scheduler) -> bool:
        """TriggerDecommissionStep + kill: issue graceful kills,
        done when every task is terminally stopped."""
        all_done = True
        for name in task_names:
            info = scheduler.state_store.fetch_task(name)
            if info is None:
                continue
            status = scheduler.state_store.fetch_status(name)
            if status is not None and status.state.is_terminal:
                continue
            grace = grace_by_full.get(name, 0.0)
            scheduler.task_killer.kill(info.task_id, grace)
            all_done = False
        return all_done

    def unreserve(scheduler) -> bool:
        for name in task_names:
            for reservation in scheduler.ledger.for_task(name):
                scheduler.ledger.release(reservation.reservation_id)
                scheduler.metrics.incr("operations.unreserve")
        return True

    def erase(scheduler) -> bool:
        for name in task_names:
            scheduler.state_store.clear_task(name)
        return True

    return [
        ActionStep(f"kill-{instance}", kill_tasks, assets=asset),
        ActionStep(f"unreserve-{instance}", unreserve, assets=asset),
        ActionStep(f"erase-{instance}", erase, assets=asset),
    ]


def build_scale_in_phase(
    pod,
    index: int,
    shrink_action,
    drain_action,
    to_count: int,
) -> Phase:
    """The autoscale scale-in choreography, one serial phase:

        shrink      the count verb — the victim becomes SURPLUS first,
                    so the recovery scan stops owning it before
                    anything dies (killing a still-owned instance
                    would race a recovery relaunch)
        drain       waits out the router drain grace; the phase's
                    ``decommission_targets`` flipped the victim's
                    /v1/endpoints rows to draining:true the moment the
                    phase was created, so by the time this step
                    completes the front door stopped placing
        kill/unreserve/erase
                    the decommission factory's step triple

    Restart-safe: shrink is idempotent (the count verb no-ops at the
    target), the teardown steps are idempotent, and a failover that
    lost the drain clock re-drains for the FULL grace — conservative,
    never shorter."""
    instance = pod_instance_name(pod.type, index)
    asset = {instance}
    task_names = sorted(
        task_full_name(pod.type, index, t.name) for t in pod.tasks
    )
    grace_by_full = {
        task_full_name(pod.type, index, t.name): t.kill_grace_period_s
        for t in pod.tasks
    }
    steps = [
        ActionStep(f"shrink-{pod.type}-to-{to_count}", shrink_action,
                   assets=asset),
        ActionStep(f"drain-{instance}", drain_action, assets=asset),
        *instance_teardown_steps(
            pod.type, index, task_names, grace_by_full
        ),
    ]
    phase = Phase(f"scale-in-{instance}", steps, SerialStrategy())
    phase.decommission_targets = {instance}
    return phase
