"""Failure monitors: when does TRANSIENT escalate to PERMANENT?

Reference: recovery/monitor/ — NeverFailureMonitor (default: always
relaunch in place), TimedFailureMonitor.java:20-60 (a task failing
continuously for longer than ReplacementFailurePolicy's
permanent-failure-timeout is declared permanently failed),
TestingFailureMonitor (fault injection for the sim harness).
"""

from __future__ import annotations

import time
from typing import Dict, Iterable, Optional

from dcos_commons_tpu.common import TaskStatus


class FailureMonitor:
    def has_failed_permanently(self, task_name: str, status: TaskStatus) -> bool:
        raise NotImplementedError

    def clear(self, task_name: str) -> None:
        pass


class NeverFailureMonitor(FailureMonitor):
    def has_failed_permanently(self, task_name: str, status: TaskStatus) -> bool:
        return False


class TimedFailureMonitor(FailureMonitor):
    """Permanent once a task has been failing for longer than
    ``permanent_failure_timeout_s`` (measured from the first observed
    failure; cleared when the task recovers)."""

    def __init__(self, permanent_failure_timeout_s: float,
                 clock=time.monotonic):
        self._timeout = permanent_failure_timeout_s
        self._first_failure: Dict[str, float] = {}
        self._clock = clock

    def has_failed_permanently(self, task_name: str, status: TaskStatus) -> bool:
        # called for statuses already classified as needing recovery
        # (any terminal state short of the goal, incl. KILLED/LOST)
        if not status.state.is_terminal:
            self.clear(task_name)
            return False
        now = self._clock()
        first = self._first_failure.setdefault(task_name, now)
        return (now - first) >= self._timeout

    def clear(self, task_name: str) -> None:
        self._first_failure.pop(task_name, None)


class TestingFailureMonitor(FailureMonitor):
    """Fault injection: the named tasks always escalate to PERMANENT."""

    __test__ = False  # not a pytest class

    def __init__(self, permanent_tasks: Optional[Iterable[str]] = None):
        self.permanent_tasks = set(permanent_tasks or [])

    def has_failed_permanently(self, task_name: str, status: TaskStatus) -> bool:
        return status.state.is_terminal and task_name in self.permanent_tasks
