"""Reconciler: align stored task state with agent reality at startup.

Reference: scheduler/ExplicitReconciler.java + framework/
ImplicitReconciler.java — on (re)registration the scheduler asks the
master for the status of every known task and gates offer processing
until the answers arrive (AbstractScheduler.java:163-184).  Here the
agents are authoritative: any task the store believes is live but no
agent knows is synthesized as TASK_LOST, which flows through the
normal status path and triggers recovery.  This is what makes the
WAL-before-launch discipline safe: a crash between WAL and launch
leaves a STAGING record that reconciliation converts to LOST.
"""

from __future__ import annotations

from typing import List

from dcos_commons_tpu.agent.base import Agent
from dcos_commons_tpu.common import TaskState, TaskStatus
from dcos_commons_tpu.state.state_store import StateStore


class Reconciler:
    def __init__(self, state_store: StateStore, agent: Agent):
        self._state_store = state_store
        self._agent = agent
        self._done = False

    @property
    def is_reconciled(self) -> bool:
        return self._done

    def reconcile(self) -> List[TaskStatus]:
        """Returns synthesized LOST statuses for vanished tasks."""
        # explicit reconciliation: agents that report transitions
        # edge-triggered (LocalProcessAgent) re-arm the CURRENT state
        # of live tasks for the next poll — without this, statuses a
        # dead predecessor drained but never acted on are lost, and an
        # adopted task can sit at store-STAGING forever
        request = getattr(self._agent, "reconcile", None)
        if callable(request):
            request()
        active = self._agent.active_task_ids()
        synthesized: List[TaskStatus] = []
        for name, status in self._state_store.fetch_statuses().items():
            if status.state.is_terminal:
                continue
            if status.task_id not in active:
                synthesized.append(
                    TaskStatus(
                        task_id=status.task_id,
                        state=TaskState.LOST,
                        message="reconciliation: agent does not know this task",
                        agent_id=status.agent_id,
                    )
                )
        self._done = True
        return synthesized

    def reset(self) -> None:
        self._done = False
