# sdklint: disable-file=no-gpus-resource — the rule definitions below
# necessarily name the banned token to detect it
"""The sdklint rule catalog.

Each rule is a class with an ``id`` (the suppression token), a
docstring (rendered by ``--catalog``), ``applies_to`` (path scoping)
and ``check`` (AST pass -> findings).  Rules encode invariants this
codebase actually relies on — the PR-1 offer-cycle fast path's
generation stamps and event-driven loop, the BASELINE resource
vocabulary, and the lock discipline the runtime's 20+ ``_lock``
owners promise — not generic style nits (those live in the build
gate, tests/test_build_gate.py).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Set, Tuple

from dcos_commons_tpu.analysis.linter import Finding, LintContext

_MUTATOR_METHODS = {
    "append", "add", "extend", "insert", "pop", "popitem", "clear",
    "update", "setdefault", "discard", "remove", "appendleft",
}


def _self_attr_writes(node: ast.AST) -> Iterator[Tuple[str, ast.AST]]:
    """Yield (attr_name, node) for every write/mutation of a ``self``
    attribute inside ``node``: plain/aug/ann assignment, subscript
    stores and deletes (``self.x[k] = v``), and calls to mutating
    container methods (``self.x.pop(...)``)."""
    for sub in ast.walk(node):
        targets: List[ast.AST] = []
        if isinstance(sub, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            # copy: the tuple-unpacking expansion below appends to this
            # list, which must never mutate the AST node itself
            targets = (
                list(sub.targets) if isinstance(sub, ast.Assign)
                else [sub.target]
            )
        elif isinstance(sub, ast.Delete):
            targets = list(sub.targets)
        for target in targets:
            if isinstance(target, ast.Tuple):
                targets.extend(target.elts)
                continue
            base = target
            if isinstance(base, ast.Subscript):
                base = base.value
            if (
                isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self"
            ):
                yield base.attr, sub
        if (
            isinstance(sub, ast.Call)
            and isinstance(sub.func, ast.Attribute)
            and sub.func.attr in _MUTATOR_METHODS
        ):
            owner = sub.func.value
            if (
                isinstance(owner, ast.Attribute)
                and isinstance(owner.value, ast.Name)
                and owner.value.id == "self"
            ):
                yield owner.attr, sub


def _is_self_attr(node: ast.AST, names: Set[str]) -> bool:
    return (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
        and node.attr in names
    )


class Rule:
    id = ""
    description = ""

    def applies_to(self, ctx: LintContext) -> bool:
        return ctx.tree is not None

    def check(self, ctx: LintContext) -> List[Finding]:
        raise NotImplementedError


class NoBlockingSleepRule(Rule):
    """``time.sleep`` in library code busy-waits what the event-driven
    scheduler loop already signals: status arrival and HTTP mutations
    ``nudge()`` the loop awake (scheduler/scheduler.py:232), so hot
    paths must park on ``Event.wait``/``Condition.wait`` instead of
    sleeping.  Scope: all of ``dcos_commons_tpu/`` except ``testing/``
    (tick harnesses legitimately pace fake time).  Polling a resource
    no event covers (e.g. a foreign pid you cannot ``wait()`` on)
    belongs under an explaining ``# sdklint: disable``."""

    id = "no-blocking-sleep"
    description = "time.sleep in scheduler/plan/offer hot paths"

    def applies_to(self, ctx: LintContext) -> bool:
        return (
            ctx.tree is not None
            and ctx.rel.startswith("dcos_commons_tpu/")
            and not ctx.rel.startswith("dcos_commons_tpu/testing/")
        )

    def check(self, ctx: LintContext) -> List[Finding]:
        out = []
        sleep_aliases = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ImportFrom) and node.module == "time":
                sleep_aliases |= {
                    a.asname or a.name for a in node.names if a.name == "sleep"
                }
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            hit = (
                isinstance(func, ast.Attribute)
                and func.attr == "sleep"
                and isinstance(func.value, ast.Name)
                and func.value.id in ("time", "_time")
            ) or (
                isinstance(func, ast.Name) and func.id in sleep_aliases
            )
            if hit:
                out.append(ctx.finding(
                    node, self.id,
                    "time.sleep blocks the event-driven loop; wake on "
                    "nudge()/Event.wait (or document why polling is "
                    "correct here)",
                ))
        return out


class LedgerMutationRule(Rule):
    """``SliceInventory``'s per-view snapshot caches, inverted field
    indexes, and free-chip buckets are all synced off the generation
    counters (``ReservationLedger._generation`` / per-host journal,
    ``SliceInventory._topology_gen``), so host state may only change
    through methods that bump the generation counter — a mutation
    that skips the bump serves stale offers AND stale placement
    candidates forever (an index that silently diverges from the
    ledger mis-routes every future placement).  Two checks: public
    methods of the two classes that mutate tracked host state must
    write the generation attribute in the same method, and no code
    anywhere may write the cache/index internals through a
    non-``self`` receiver — index maintenance goes through the
    generation-bumping mutators, full stop."""

    id = "ledger-mutation"
    description = "ledger/inventory host state mutated without a generation bump"

    _TRACKED = {
        "ReservationLedger": (
            {"_cache", "_by_host", "_by_task", "_host_gen"}, "_generation",
        ),
        "SliceInventory": (
            {"_hosts", "_down", "_preempted", "_maintenance",
             "_host_topo_gen"}, "_topology_gen",
        ),
    }
    # every tracked attr plus the generation counters and the snapshot
    # cache / index structures: writable through `self` inside the
    # owning class only
    _INTERNALS = (
        {attr for attrs, _ in _TRACKED.values() for attr in attrs}
        | {gen for _, gen in _TRACKED.values()}
        | {
            "_view_caches", "_field_indexes", "_ordinal_cache",
            "_up_ids_cache", "_hosts_by_id",
            # scan-order state (health plane's suspect demotion): an
            # external write would desync ordinals from the per-view
            # ordered lists and break indexed-vs-full-scan equivalence
            "_suspect", "_suspect_sources", "_order_gen",
            "_scan_cache", "_scan_cache_gen",
        }
    )

    def check(self, ctx: LintContext) -> List[Finding]:
        out = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef) and node.name in self._TRACKED:
                out += self._check_class(ctx, node)
        out += self._check_reach_in(ctx)
        return out

    def _check_class(self, ctx, cls) -> List[Finding]:
        tracked, gen_attr = self._TRACKED[cls.name]
        out = []
        for method in cls.body:
            if not isinstance(method, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if method.name.startswith("_"):
                # underscore helpers (_index/_unindex/_load/__init__)
                # run under a bumping public caller; the public surface
                # is where the discipline is enforced
                continue
            touched = [
                (attr, sub) for attr, sub in _self_attr_writes(method)
                if attr in tracked
            ]
            if not touched:
                continue
            bumps = any(
                attr == gen_attr for attr, _ in _self_attr_writes(method)
            )
            if not bumps:
                for attr, sub in touched:
                    out.append(ctx.finding(
                        sub, self.id,
                        f"{cls.name}.{method.name} mutates self.{attr} "
                        f"without bumping self.{gen_attr}: snapshot "
                        "caches keyed on the generation go stale",
                    ))
        return out

    def _check_reach_in(self, ctx) -> List[Finding]:
        out = []
        for node in ast.walk(ctx.tree):
            for attr, sub in self._external_writes(node):
                out.append(ctx.finding(
                    sub, self.id,
                    f"external write to ledger/inventory internal "
                    f".{attr}: go through the generation-bumping API",
                ))
        return out

    def _external_writes(self, node) -> Iterator[Tuple[str, ast.AST]]:
        targets: List[ast.AST] = []
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
        for target in targets:
            base = target
            # unwrap nested subscripts: index maintenance writes like
            # inv._field_indexes['zone']['z'] = ... are still writes
            # to the internal
            while isinstance(base, ast.Subscript):
                base = base.value
            if (
                isinstance(base, ast.Attribute)
                and base.attr in self._INTERNALS
                and not (
                    isinstance(base.value, ast.Name)
                    and base.value.id == "self"
                )
            ):
                yield base.attr, node
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _MUTATOR_METHODS
        ):
            owner = node.func.value
            if (
                isinstance(owner, ast.Attribute)
                and owner.attr in self._INTERNALS
                and not (
                    isinstance(owner.value, ast.Name)
                    and owner.value.id == "self"
                )
            ):
                yield owner.attr, node


class LockDisciplineRule(Rule):
    """A class that creates a ``threading.Lock``/``RLock``/``Condition``
    in ``__init__`` promises its shared mutable state is written under
    that lock.  The guarded set is inferred: any ``self`` attribute
    written inside a ``with self.<lock>:`` block (outside ``__init__``)
    is shared state, and every other write to it must also hold the
    lock.  Methods named ``*_locked`` declare "caller holds the lock"
    (the runtime/runner.py convention) and count as guarded.  Reads
    stay un-flagged (lock-free reads of snapshots are a deliberate
    idiom here); a genuinely single-threaded write path carries an
    explaining ``# sdklint: disable``."""

    id = "lock-discipline"
    description = "guarded attribute written outside `with self._lock`"

    def check(self, ctx: LintContext) -> List[Finding]:
        out = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                out += self._check_class(ctx, node)
        return out

    @staticmethod
    def _lock_attrs(cls: ast.ClassDef) -> Set[str]:
        """Names of self attrs assigned a threading lock in __init__."""
        locks: Set[str] = set()
        for method in cls.body:
            if not isinstance(method, ast.FunctionDef) or \
                    method.name != "__init__":
                continue
            for sub in ast.walk(method):
                if not isinstance(sub, ast.Assign):
                    continue
                value = sub.value
                if not (
                    isinstance(value, ast.Call)
                    and isinstance(value.func, ast.Attribute)
                    and value.func.attr in ("Lock", "RLock", "Condition")
                    and isinstance(value.func.value, ast.Name)
                    and value.func.value.id == "threading"
                ):
                    continue
                for target in sub.targets:
                    if isinstance(target, ast.Attribute) and \
                            isinstance(target.value, ast.Name) and \
                            target.value.id == "self":
                        locks.add(target.attr)
        return locks

    def _method_writes(
        self, method: ast.AST, lock_attrs: Set[str]
    ) -> List[Tuple[str, ast.AST, bool]]:
        """(attr, node, under_lock) for every self-attr write, walking
        the statement tree with a with-lock depth counter."""
        writes: List[Tuple[str, ast.AST, bool]] = []

        def visit(node: ast.AST, held: bool) -> None:
            if isinstance(node, ast.With):
                holds = held or any(
                    _is_self_attr(item.context_expr, lock_attrs)
                    for item in node.items
                )
                for child in node.body:
                    visit(child, holds)
                return
            for attr, sub in _direct_writes(node):
                writes.append((attr, sub, held))
            for child in ast.iter_child_nodes(node):
                # excepthandler is not an ast.stmt but carries a
                # statement body — error-recovery paths are exactly
                # where forgotten locking hides
                if isinstance(child, (ast.stmt, ast.excepthandler)):
                    visit(child, held)

        def _direct_writes(node):
            """Writes attributable to THIS statement (not recursing
            into compound bodies, which visit() handles)."""
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign,
                                 ast.Delete, ast.Expr)):
                yield from _self_attr_writes(node)

        for stmt in method.body:
            visit(stmt, False)
        return writes

    def _check_class(self, ctx, cls) -> List[Finding]:
        lock_attrs = self._lock_attrs(cls)
        if not lock_attrs:
            return []
        per_method: Dict[str, List[Tuple[str, ast.AST, bool]]] = {}
        for method in cls.body:
            if not isinstance(method, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    or method.name == "__init__":
                continue
            writes = self._method_writes(method, lock_attrs)
            if method.name.endswith("_locked"):
                writes = [(attr, node, True) for attr, node, _ in writes]
            per_method[method.name] = writes
        guarded: Set[str] = {
            attr
            for writes in per_method.values()
            for attr, _, held in writes
            if held
        } - lock_attrs
        out = []
        for name, writes in per_method.items():
            for attr, node, held in writes:
                if attr in guarded and not held:
                    out.append(ctx.finding(
                        node, self.id,
                        f"{cls.name}.{name} writes self.{attr} outside "
                        f"`with self.{sorted(lock_attrs)[0]}` but other "
                        "methods guard it — racy write",
                    ))
        return out


class NoGpusVocabularyRule(Rule):
    """BASELINE invariant: the TPU-first resource model has no ``gpus``
    scalar — accelerators are the pod-level ``tpu:`` block
    (specification/specs.py:9).  Any identifier, dict key, or exact
    string ``"gpus"`` reintroduces the vocabulary this rebuild
    deliberately removed (prose in docstrings is fine)."""

    id = "no-gpus-resource"
    description = "`gpus` resource vocabulary (BASELINE bans it)"

    def check(self, ctx: LintContext) -> List[Finding]:
        out = []
        for node in ast.walk(ctx.tree):
            hit = None
            if isinstance(node, ast.Name) and node.id == "gpus":
                hit = "identifier"
            elif isinstance(node, ast.Attribute) and node.attr == "gpus":
                hit = "attribute"
            elif isinstance(node, ast.arg) and node.arg == "gpus":
                hit = "argument"
            elif isinstance(node, ast.keyword) and node.arg == "gpus":
                hit = "keyword"
            elif isinstance(node, ast.Constant) and node.value == "gpus":
                hit = "string"
            if hit is not None:
                out.append(ctx.finding(
                    node, self.id,
                    f"{hit} 'gpus': accelerators are the pod-level "
                    "tpu: block, not a gpus scalar",
                ))
        return out


class SwallowedExceptionRule(Rule):
    """``except Exception: pass`` hides the stack trace the on-call
    engineer needed.  A broad handler must do *something* — log,
    count, return a fallback, or re-raise; a handler whose body is
    only ``pass``/``continue`` is flagged.  Where drop-and-continue
    is genuinely correct (a broken listener must not break intake),
    say so next to a ``# sdklint: disable``."""

    id = "swallowed-exception"
    description = "except Exception/bare except with a pass-only body"

    _BROAD = ("Exception", "BaseException")

    def _is_broad(self, type_node) -> bool:
        if type_node is None:
            return True
        if isinstance(type_node, ast.Name):
            return type_node.id in self._BROAD
        if isinstance(type_node, ast.Attribute):
            return type_node.attr in self._BROAD
        if isinstance(type_node, ast.Tuple):
            return any(self._is_broad(e) for e in type_node.elts)
        return False

    def check(self, ctx: LintContext) -> List[Finding]:
        out = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not self._is_broad(node.type):
                continue
            swallows = all(
                isinstance(stmt, (ast.Pass, ast.Continue)) or (
                    isinstance(stmt, ast.Expr)
                    and isinstance(stmt.value, ast.Constant)
                )
                for stmt in node.body
            )
            if swallows:
                out.append(ctx.finding(
                    node, self.id,
                    "broad except with a pass-only body swallows the "
                    "error; log it, narrow the type, or re-raise",
                ))
        return out


class TracerUnsafeCastRule(Rule):
    """Inside a ``jit``/``shard_map``/``pmap``-decorated function the
    arguments are tracers; ``float()``/``int()``/``bool()`` and
    ``np.asarray``/``np.array`` force host materialization and raise
    ``TracerConversionError`` at trace time — or worse, silently
    constant-fold a value that should have stayed symbolic.  Use
    ``jnp`` ops and let values stay on device."""

    id = "jit-tracer-cast"
    description = "host-side cast (float/int/np.asarray) under jit/shard_map"

    _DECORATOR_NAMES = {"jit", "shard_map", "pmap"}
    _CAST_NAMES = {"float", "int", "bool"}
    _NP_MODULES = {"np", "numpy", "onp"}
    _NP_FUNCS = {"asarray", "array"}

    def _is_traced_decorator(self, decorator: ast.AST) -> bool:
        for sub in ast.walk(decorator):
            if isinstance(sub, ast.Name) and sub.id in self._DECORATOR_NAMES:
                return True
            if isinstance(sub, ast.Attribute) and \
                    sub.attr in self._DECORATOR_NAMES:
                return True
        return False

    def check(self, ctx: LintContext) -> List[Finding]:
        out = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not any(
                self._is_traced_decorator(d) for d in node.decorator_list
            ):
                continue
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                func = sub.func
                bad = None
                if isinstance(func, ast.Name) and \
                        func.id in self._CAST_NAMES and sub.args:
                    bad = f"{func.id}()"
                elif (
                    isinstance(func, ast.Attribute)
                    and func.attr in self._NP_FUNCS
                    and isinstance(func.value, ast.Name)
                    and func.value.id in self._NP_MODULES
                ):
                    bad = f"{func.value.id}.{func.attr}()"
                if bad:
                    out.append(ctx.finding(
                        sub, self.id,
                        f"{bad} inside jit/shard_map-traced "
                        f"{node.name}() materializes a tracer on host; "
                        "keep it in jnp",
                    ))
        return out


class SpanLeakRule(Rule):
    """A ``.span(...)`` call on a trace recorder returns a LIVE span:
    it enters the flight recorder only when closed, and its children
    reference its id — a leaked span silently drops a region of the
    timeline and leaves orphan children.  Every ``<tracer>.span(...)``
    result must therefore be closed on all paths: used directly as a
    ``with`` context, or bound to a name that is ``end()``-ed (or
    returned/yielded — ownership moves to the caller).  A bare
    expression statement discards the span and always leaks.
    ``event()`` closes itself and is exempt.  Receivers are recognized
    by name (``trace``/``tracer``/``recorder`` variants), so the rule
    follows the subsystem's own naming convention."""

    id = "span-leak"
    description = "trace span not closed via `with` or end()"

    _RECEIVERS = {
        "trace", "tracer", "_tracer", "recorder", "_recorder",
        "NULL_TRACER",
    }

    def _is_span_call(self, node: ast.AST) -> bool:
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "span"):
            return False
        owner = node.func.value
        name = None
        if isinstance(owner, ast.Name):
            name = owner.id
        elif isinstance(owner, ast.Attribute):
            name = owner.attr
        return name in self._RECEIVERS

    @staticmethod
    def _iter_scope(node: ast.AST) -> Iterator[ast.AST]:
        """Walk a function (or module) body without descending into
        nested function scopes — each scope is analyzed once, against
        its own end()/return statements."""
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            yield child
            yield from SpanLeakRule._iter_scope(child)

    def check(self, ctx: LintContext) -> List[Finding]:
        out: List[Finding] = []
        scopes: List[ast.AST] = [ctx.tree] + [
            n for n in ast.walk(ctx.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        for scope in scopes:
            out += self._check_scope(ctx, scope)
        return out

    def _check_scope(self, ctx: LintContext, scope: ast.AST) -> List[Finding]:
        nodes = list(self._iter_scope(scope))
        # span calls that are a `with` context expression are closed
        with_exprs = {
            id(item.context_expr)
            for node in nodes if isinstance(node, ast.With)
            for item in node.items
        }
        # names with a .end() call, returned, or yielded in this scope
        closed_names: Set[str] = set()
        for node in nodes:
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "end"
                    and isinstance(node.func.value, ast.Name)):
                closed_names.add(node.func.value.id)
            if isinstance(node, (ast.Return, ast.Yield)) and \
                    isinstance(node.value, ast.Name):
                closed_names.add(node.value.id)
            if isinstance(node, ast.With):
                # `s = tracer.span(...)` later entered as `with s:`
                for item in node.items:
                    if isinstance(item.context_expr, ast.Name):
                        closed_names.add(item.context_expr.id)
        out: List[Finding] = []
        for node in nodes:
            # bare `tracer.span(...)` statement: discarded, never closed
            if isinstance(node, ast.Expr) and self._is_span_call(node.value):
                out.append(ctx.finding(
                    node, self.id,
                    "span discarded unclosed; use `with ....span(...)` "
                    "(or .event() for instantaneous records)",
                ))
                continue
            if not isinstance(node, ast.Assign) or \
                    not self._is_span_call(node.value):
                continue
            if id(node.value) in with_exprs:
                continue
            targets = [
                t.id for t in node.targets if isinstance(t, ast.Name)
            ]
            if targets and not any(t in closed_names for t in targets):
                out.append(ctx.finding(
                    node, self.id,
                    f"span bound to {targets[0]!r} is never end()-ed "
                    "on this scope's paths; close it with `with` or an "
                    "explicit end()",
                ))
        return out


class LeaseGatedMutationRule(Rule):
    """HA invariant (dcos_commons_tpu/ha/): in scheduler-path modules,
    every persisted mutation must flow through a store class
    (StateStore/ConfigStore/ReservationLedger/OptionsStore/...) —
    store objects are constructed over the wired persister, which in
    HA mode is the lease-fenced writer, so a raw
    ``persister.set/apply/recursive_delete`` in scheduler logic is a
    write that could bypass the failover fence (and definitely
    bypasses the one place the layering is auditable).  Scope: the
    scheduler-path packages below; store/fence modules themselves
    (state/, storage/, multi/store.py, ha/election.py) and testing/
    are exempt.  A deliberate raw write carries an explaining
    ``# sdklint: disable``.

    Division of labor with durcheck's ``dur-unfenced-write``: this
    rule owns DIRECT raw mutations inside ``_SCOPED`` (single-file,
    cheap, runs on every lint); durcheck owns raw mutations OUTSIDE
    the scope that are nevertheless reachable from scheduler-path
    code over the interprocedural call graph — durcheck skips every
    site in ``_SCOPED``, so one site is never double-reported."""

    id = "lease-gated-mutation"
    description = "raw persister mutation in a scheduler path (bypasses the lease-fenced store layer)"

    _MUTATIONS = {"set", "apply", "recursive_delete", "clear_all_data"}
    _SCOPED = (
        "dcos_commons_tpu/scheduler/",
        "dcos_commons_tpu/runtime/",
        "dcos_commons_tpu/recovery/",
        "dcos_commons_tpu/plan/",
        "dcos_commons_tpu/http/",
        "dcos_commons_tpu/multi/",
        "dcos_commons_tpu/decommission/",
        "dcos_commons_tpu/uninstall/",
        "dcos_commons_tpu/ha/",
    )
    _EXEMPT = (
        # store classes: the layer raw mutations BELONG in
        "dcos_commons_tpu/multi/store.py",
        # the fence itself: lease-record writes run below the fence
        "dcos_commons_tpu/ha/election.py",
    )

    def applies_to(self, ctx: LintContext) -> bool:
        return (
            ctx.tree is not None
            and any(ctx.rel.startswith(p) for p in self._SCOPED)
            and ctx.rel not in self._EXEMPT
        )

    @staticmethod
    def _receiver_name(node: ast.AST):
        if isinstance(node, ast.Name):
            return node.id
        if isinstance(node, ast.Attribute):
            return node.attr
        return None

    def check(self, ctx: LintContext) -> List[Finding]:
        out = []
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in self._MUTATIONS):
                continue
            name = self._receiver_name(node.func.value)
            if name is None:
                continue
            lowered = name.lower()
            if "persister" not in lowered and "backend" not in lowered:
                continue
            out.append(ctx.finding(
                node, self.id,
                f"raw {name}.{node.func.attr}(...) in a scheduler "
                "path: route the mutation through a store class so it "
                "flows through the (lease-fenced) wired persister",
            ))
        return out


class MetricCardinalityRule(Rule):
    """Metric names built from unbounded runtime values (task ids,
    request ids, host ids interpolated into ``Metrics.incr``/
    ``gauge``/``time`` names) grow the registry — and every
    Prometheus scrape, snapshot, and history ring — without bound:
    ten thousand relaunches mint ten thousand immortal series.
    Dynamic name parts must be BOUNDED vocabularies (enum ``.value``,
    a literal loop), registered in ``METRIC_CARDINALITY_ALLOWLIST``
    (for prefixes whose id-space is bounded elsewhere, with the bound
    stated), or carry an explaining ``# sdklint: disable``.  The
    check flags f-string/%%/.format()/concat name arguments whose
    interpolated expression terminates in an id-shaped identifier
    (``*_id``, ``task_id``, ``request_id``, ``pid``, ``task_name``,
    ...)."""

    id = "metric-cardinality"
    description = "metric name built from an unbounded runtime id"

    _METHODS = {"incr", "gauge", "time"}
    _ID_SHAPED = {
        "pid", "tid", "uuid", "task_name", "task", "request",
        "hostname",
    }

    def applies_to(self, ctx: LintContext) -> bool:
        return (
            ctx.tree is not None
            and ctx.rel.startswith("dcos_commons_tpu/")
            and not ctx.rel.startswith("dcos_commons_tpu/testing/")
        )

    @classmethod
    def _is_id_shaped(cls, name: str) -> bool:
        lowered = name.lower().lstrip("_")
        return (
            lowered in cls._ID_SHAPED
            or lowered.endswith("_id")
            or lowered == "id"
            or lowered.endswith("_uuid")
        )

    @classmethod
    def _terminal_name(cls, node: ast.AST):
        """The identifier a dynamic expression terminates in:
        ``pid`` for ``pid``, ``task_id`` for ``status.task_id``,
        ``task_id`` for ``info.task_id.upper()``."""
        if isinstance(node, ast.Name):
            return node.id
        if isinstance(node, ast.Attribute):
            return node.attr
        if isinstance(node, ast.Call):
            return cls._terminal_name(node.func.value) if isinstance(
                node.func, ast.Attribute
            ) else None
        if isinstance(node, ast.FormattedValue):
            return cls._terminal_name(node.value)
        return None

    def _dynamic_parts(self, arg: ast.AST):
        """Yield the non-literal sub-expressions of a metric-name
        argument, however it was concatenated."""
        if isinstance(arg, ast.JoinedStr):
            for part in arg.values:
                if isinstance(part, ast.FormattedValue):
                    yield part.value
        elif isinstance(arg, ast.BinOp) and isinstance(
            arg.op, (ast.Add, ast.Mod)
        ):
            for side in (arg.left, arg.right):
                if isinstance(side, ast.Tuple):
                    for elt in side.elts:
                        if not isinstance(elt, ast.Constant):
                            yield elt
                elif isinstance(side, (ast.BinOp, ast.JoinedStr)):
                    yield from self._dynamic_parts(side)
                elif not isinstance(side, ast.Constant):
                    yield side
        elif isinstance(arg, ast.Call) and isinstance(
            arg.func, ast.Attribute
        ) and arg.func.attr == "format":
            yield from arg.args
            yield from (kw.value for kw in arg.keywords)

    @staticmethod
    def _literal_prefix(arg: ast.AST) -> str:
        if isinstance(arg, ast.JoinedStr) and arg.values and isinstance(
            arg.values[0], ast.Constant
        ):
            return str(arg.values[0].value)
        if isinstance(arg, ast.BinOp) and isinstance(
            arg.left, ast.Constant
        ):
            return str(arg.left.value)
        return ""

    def check(self, ctx: LintContext) -> List[Finding]:
        out = []
        for node in ast.walk(ctx.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in self._METHODS
                and node.args
            ):
                continue
            receiver = node.func.value
            receiver_name = (
                receiver.id if isinstance(receiver, ast.Name)
                else receiver.attr if isinstance(receiver, ast.Attribute)
                else ""
            )
            if "metric" not in receiver_name.lower() and \
                    receiver_name.lower() != "registry":
                continue
            name_arg = node.args[0]
            prefix = self._literal_prefix(name_arg)
            if any(
                prefix.startswith(allowed)
                for allowed in METRIC_CARDINALITY_ALLOWLIST
            ):
                continue
            for part in self._dynamic_parts(name_arg):
                terminal = self._terminal_name(part)
                if terminal is not None and self._is_id_shaped(terminal):
                    out.append(ctx.finding(
                        node, self.id,
                        f"metric name interpolates {terminal!r} (an "
                        "unbounded runtime id): every distinct value "
                        "mints an immortal series in the registry, "
                        "scrape, and history ring — key by a bounded "
                        "vocabulary, register the prefix in "
                        "METRIC_CARDINALITY_ALLOWLIST with its bound, "
                        "or suppress with the bound stated",
                    ))
                    break
        return out


class RouterStatsStalenessRule(Rule):
    """Router invariant (ISSUE 12): every pod gauge the router acts
    on must cross the staleness gate — ``router/telemetry.py`` parses
    raw ``GET /stats`` dicts exactly once into ``PodTelemetry`` and
    answers load questions through freshness-aware accessors, so a
    wedged pod's last-good numbers can never steer placement.  Any
    OTHER router module subscripting or ``.get()``-ing a stats-named
    dict is reaching around the gate: flagged.  Scope:
    ``dcos_commons_tpu/router/`` except the telemetry module itself.
    A genuinely gauge-free read (router's own snapshot assembly)
    carries an explaining ``# sdklint: disable``."""

    id = "router-stats-staleness"
    description = "router code reads a raw stats dict outside the telemetry staleness gate"

    _GATE_MODULE = "dcos_commons_tpu/router/telemetry.py"

    def applies_to(self, ctx: LintContext) -> bool:
        return (
            ctx.tree is not None
            and ctx.rel.startswith("dcos_commons_tpu/router/")
            and ctx.rel != self._GATE_MODULE
        )

    @staticmethod
    def _terminal_name(node: ast.AST):
        if isinstance(node, ast.Name):
            return node.id
        if isinstance(node, ast.Attribute):
            return node.attr
        return None

    @classmethod
    def _is_stats_named(cls, node: ast.AST) -> bool:
        name = cls._terminal_name(node)
        return name is not None and "stats" in name.lower()

    def check(self, ctx: LintContext) -> List[Finding]:
        out = []
        for node in ast.walk(ctx.tree):
            hit = None
            if isinstance(node, ast.Subscript) and \
                    self._is_stats_named(node.value):
                hit = f"{self._terminal_name(node.value)}[...]"
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "get"
                and self._is_stats_named(node.func.value)
            ):
                hit = f"{self._terminal_name(node.func.value)}.get(...)"
            if hit is not None:
                out.append(ctx.finding(
                    node, self.id,
                    f"raw stats access {hit}: parse pod gauges in "
                    "router/telemetry.py (PodTelemetry.observe) and "
                    "read them through its staleness-gated accessors "
                    "— a wedged pod's last-good numbers must not "
                    "steer placement",
                ))
        return out


# metric-name prefixes whose dynamic part is bounded by something
# other than the interpolated identifier's type — each entry states
# the bound, which is the contract a reviewer checks when one is
# added.  (Deliberately empty at ship: the one in-tree dynamic-id
# metric, ha.replication.lag.<puller>, carries an inline suppression
# with its bound instead, keeping the waiver next to the code.)
METRIC_CARDINALITY_ALLOWLIST: tuple = ()


class HealthActionPurityRule(Rule):
    """ROADMAP item 2's layering invariant: the health plane DECIDES,
    only the plan engine and journaled scheduler verbs ACT.  Code in
    ``dcos_commons_tpu/health/`` (detectors, the action governor)
    must not mutate the ledger, state store, or persister directly —
    a detector that writes state bypasses the audit trail, the
    operator's plan-verb interrupt surface, and the failover
    re-synthesis contract all at once.  Mutations belong in
    factory-built plan steps (decommission/factory.py,
    plan/builders.py) or scheduler verbs (``set_pod_count``,
    ``restart_pod``).  ``journal.py`` is exempt: the journal IS the
    audit surface and owns its own persistence backend.  A deliberate
    exception carries an explaining ``# sdklint: disable``."""

    id = "health-plan-only"
    description = (
        "health-plane code mutating ledger/state-store directly "
        "(actions must ride the plan/verb surface)"
    )

    _SCOPED = ("dcos_commons_tpu/health/",)
    _EXEMPT = ("dcos_commons_tpu/health/journal.py",)
    _MUTATIONS = {
        # ledger
        "commit", "release",
        # state store
        "store_tasks", "store_status", "store_property", "clear_task",
        "store_goal_override", "set_target_config", "clear_all_data",
        # raw persister
        "set", "apply", "recursive_delete", "wipe_namespace",
        # launch WAL
        "record",
    }

    def applies_to(self, ctx: LintContext) -> bool:
        return (
            ctx.tree is not None
            and any(ctx.rel.startswith(p) for p in self._SCOPED)
            and ctx.rel not in self._EXEMPT
        )

    @staticmethod
    def _receiver_name(node: ast.AST):
        if isinstance(node, ast.Name):
            return node.id
        if isinstance(node, ast.Attribute):
            return node.attr
        return None

    def check(self, ctx: LintContext) -> List[Finding]:
        out = []
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in self._MUTATIONS):
                continue
            name = self._receiver_name(node.func.value)
            if name is None:
                continue
            lowered = name.lower()
            if not (
                "ledger" in lowered
                or "persister" in lowered
                or "recorder" in lowered
                or lowered == "store"
                or lowered.endswith("_store")
            ):
                continue
            out.append(ctx.finding(
                node, self.id,
                f"{name}.{node.func.attr}(...) mutates system state "
                "from the health plane: route the action through a "
                "plan step (decommission/plan factories) or a "
                "journaled scheduler verb so it stays audited and "
                "operator-interruptible",
            ))
        return out


def all_rules() -> List[Rule]:
    return [
        NoBlockingSleepRule(),
        LedgerMutationRule(),
        LockDisciplineRule(),
        NoGpusVocabularyRule(),
        SwallowedExceptionRule(),
        TracerUnsafeCastRule(),
        SpanLeakRule(),
        LeaseGatedMutationRule(),
        MetricCardinalityRule(),
        RouterStatsStalenessRule(),
        HealthActionPurityRule(),
    ]


def rule_catalog() -> str:
    """Human-readable rule list for ``--catalog`` and the docs."""
    blocks = []
    for rule in all_rules():
        doc = " ".join((rule.__doc__ or "").split())
        blocks.append(f"{rule.id}: {rule.description}\n    {doc}")
    return "\n\n".join(blocks)
