"""ServiceTestRunner: boot the full scheduler stack and script it.

Reference: sdk/testing/.../ServiceTestRunner.java:38 — loads the real
service YAML (with env overrides), runs SchedulerBuilder against a
MemPersister and a mocked driver, then processes SimulationTicks.
Restart simulation: build a second runner over the same persister
(ServiceTest.java:57-77); the plans must resume mid-step.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from dcos_commons_tpu.offer.inventory import SliceInventory, TpuHost
from dcos_commons_tpu.scheduler.builder import SchedulerBuilder
from dcos_commons_tpu.scheduler.config import SchedulerConfig
from dcos_commons_tpu.scheduler.scheduler import DefaultScheduler
from dcos_commons_tpu.specification.specs import ServiceSpec
from dcos_commons_tpu.specification.yaml_spec import from_yaml
from dcos_commons_tpu.storage import MemPersister, Persister
from dcos_commons_tpu.testing.fake_agent import FakeAgent


@dataclass
class SimulationWorld:
    """Everything a tick can see/touch (reference: ClusterState +
    the runner internals Expect closures capture)."""

    scheduler: DefaultScheduler
    agent: FakeAgent
    inventory: SliceInventory
    persister: Persister
    # index into agent.launched already consumed by ExpectLaunchedTasks
    launch_watermark: int = 0
    # index into agent.kills already consumed by ExpectTaskKilled
    kill_watermark: int = 0
    extras: Dict[str, object] = field(default_factory=dict)

    @property
    def state_store(self):
        return self.scheduler.state_store

    def new_launches(self):
        return self.agent.launched[self.launch_watermark:]

    def new_kills(self):
        return self.agent.kills[self.kill_watermark:]


def cosmos_render(
    framework_dir: str,
    options: Optional[Dict] = None,
) -> Dict[str, str]:
    """CosmosRenderer analogue (sdk/testing/.../CosmosRenderer.java:24):
    render a framework's options.json defaults + user options into the
    env map its svc.yml interpolates, exactly as a package install
    would.  ServiceTest-style tests run from package options::

        env = cosmos_render("frameworks/helloworld",
                            {"world": {"count": 3}})
        runner = ServiceTestRunner(yaml_text, env=env)

    Raises tools.options.OptionsError on bad options — so a test can
    also assert that an invalid option set is rejected."""
    from dcos_commons_tpu.tools.options import load_schema, render_options

    return render_options(load_schema(framework_dir), options)


class ServiceTestRunner:
    """Builds a scheduler from YAML/spec over a (shared) persister and
    runs scripted ticks against it synchronously."""

    def __init__(
        self,
        yaml_text: Optional[str] = None,
        spec: Optional[ServiceSpec] = None,
        hosts: Optional[List[TpuHost]] = None,
        persister: Optional[Persister] = None,
        scheduler_config: Optional[SchedulerConfig] = None,
        env: Optional[Dict[str, str]] = None,
        builder_hook=None,
    ):
        if spec is None:
            if yaml_text is None:
                raise ValueError("need yaml_text or spec")
            spec = from_yaml(yaml_text, env=env)
        self.spec = spec
        self.hosts = hosts if hosts is not None else [
            TpuHost(host_id=f"host-{i}") for i in range(3)
        ]
        self.persister = persister or MemPersister()
        # sim cycles run in microseconds: the revive token bucket would
        # throttle ordinary serial-deploy step boundaries that take
        # seconds of wall clock in production.  Tests of the throttle
        # itself install their own bucket.
        self.config = scheduler_config or SchedulerConfig(
            backoff_enabled=False, revive_capacity=1_000_000
        )
        self._builder_hook = builder_hook
        self.agent = FakeAgent()
        self.inventory = SliceInventory(self.hosts)
        self.world: Optional[SimulationWorld] = None

    def build(self) -> SimulationWorld:
        builder = SchedulerBuilder(self.spec, self.config, self.persister)
        builder.set_inventory(self.inventory)
        builder.set_agent(self.agent)
        if self._builder_hook is not None:
            self._builder_hook(builder)
        scheduler = builder.build()
        self.world = SimulationWorld(
            scheduler=scheduler,
            agent=self.agent,
            inventory=self.inventory,
            persister=self.persister,
            # watermarks start at "now": a restarted runner shares the
            # agent with its predecessor and must not re-observe old
            # launches/kills
            launch_watermark=len(self.agent.launched),
            kill_watermark=len(self.agent.kills),
        )
        return self.world

    def run(self, ticks: Sequence) -> SimulationWorld:
        """Process ticks in order.  The scheduler is built lazily on
        first use so a runner can be primed (hosts added, etc.) before
        the config-update pass runs."""
        world = self.world or self.build()
        for i, tick in enumerate(ticks):
            try:
                tick.apply(world)
            except AssertionError as e:
                raise AssertionError(
                    f"tick[{i}] {tick.describe()}: {e}\n{_dump(world)}"
                ) from e
        return world

    def restart(self) -> "ServiceTestRunner":
        """Simulate a scheduler process restart: same persister and
        fleet, same agent (tasks keep running), fresh scheduler."""
        runner = ServiceTestRunner(
            spec=self.spec,
            hosts=self.hosts,
            persister=self.persister,
            scheduler_config=self.config,
            builder_hook=self._builder_hook,
        )
        runner.agent = self.agent
        runner.inventory = self.inventory
        return runner


def _dump(world: SimulationWorld) -> str:
    """Debug dump appended to every failed Expect (reference: the
    harness logs plan trees on failure)."""
    lines = ["--- simulation state ---"]
    for name, plan in world.scheduler.plans().items():
        lines.append(f"plan {name}: {plan.get_status().value}")
        for phase in plan.phases:
            lines.append(f"  phase {phase.name}: {phase.get_status().value}")
            for step in phase.steps:
                lines.append(f"    step {step.name}: {step.get_status().value}")
    lines.append(f"launched: {[i.name for i in world.agent.launched]}")
    lines.append(f"kills: {world.agent.kills}")
    statuses = {
        n: s.state.value for n, s in world.state_store.fetch_statuses().items()
    }
    lines.append(f"stored statuses: {statuses}")
    return "\n".join(lines)
