"""L5 storage: pluggable hierarchical KV persistence.

Reference: sdk/scheduler/.../storage/Persister.java:15-99 (interface),
MemPersister.java (test impl), PersisterCache.java (write-through RAM
cache), curator/CuratorPersister.java:43-110 (ZooKeeper impl with
atomic multi-op transactions).

The rebuild keeps the same contract — a hierarchical path->bytes store
with atomic multi-op transactions — but swaps ZooKeeper for a local
write-ahead-logged file store (TPU control planes run on the pod's
admin VM; a single fsync'd WAL is the idiomatic substrate, and the
interface stays pluggable for etcd later).
"""

from dcos_commons_tpu.storage.persister import (
    DeleteOp,
    MemPersister,
    Persister,
    PersisterError,
    SetOp,
    StorageError,
)
from dcos_commons_tpu.storage.file_persister import FileWalPersister
from dcos_commons_tpu.storage.cache import PersisterCache
from dcos_commons_tpu.storage.remote import (
    RemoteLocker,
    RemotePersister,
    StateServer,
)

__all__ = [
    "DeleteOp",
    "FileWalPersister",
    "MemPersister",
    "Persister",
    "PersisterCache",
    "RemoteLocker",
    "RemotePersister",
    "StateServer",
    "PersisterError",
    "SetOp",
    "StorageError",
]
