"""Serving front door (ISSUE 12): multi-pod request routing with
load-, prefix-cache- and drain-aware placement.

* ``core``       — the transport-free ``RequestRouter``
* ``telemetry``  — staleness-gated pod gauges (the only raw-stats
                   touchpoint; sdklint ``router-stats-staleness``)
* ``affinity``   — page-aligned prefix chain keys (the paging intern
                   shape) + the bounded affinity map
* ``frontdoor``  — the HTTP server, discovery + stats poll loops
"""

from dcos_commons_tpu.router.affinity import (
    AffinityMap,
    prefix_chain_keys,
)
from dcos_commons_tpu.router.core import (
    ROUTERSTATS_NAME,
    NoPodAvailableError,
    PodTransportError,
    RequestRouter,
)
from dcos_commons_tpu.router.telemetry import (
    DEFAULT_STALE_AFTER_S,
    PodTelemetry,
)

__all__ = [
    "AffinityMap",
    "DEFAULT_STALE_AFTER_S",
    "NoPodAvailableError",
    "PodTelemetry",
    "PodTransportError",
    "ROUTERSTATS_NAME",
    "RequestRouter",
    "prefix_chain_keys",
]
