"""Paged KV memory for the serving engine: page allocator, admission
budget, and the refcounted prefix cache.

The slot pool (ISSUE 6) carved KV memory as SLOTS x MAX_LEN rows: a
10-token reply stranded an entire MAX_LEN row.  This module is the
host-side half of the replacement — KV memory becomes a fixed arena
of ``page_tokens``-sized pages and each request holds a PAGE TABLE
(virtual position ``p`` lives in physical page ``table[p //
page_tokens]``), so a short request holds exactly the pages its
tokens need and the freed remainder admits more concurrent requests
under the SAME HBM budget (vLLM's PagedAttention shape).

Everything here is jax-free bookkeeping driven by the engine's loop
thread; the device half (arena tensors, gather-attention) lives in
``models/decode.py`` + ``serve/pool.py``.  Three pieces:

* **Free-list allocator with admission budgeting** — a request is
  admitted only when its WORST-CASE page need (every token decoded,
  no early EOS) fits ``available - reserved``; the need is then
  RESERVED and consumed lazily as positions cross page boundaries, so
  an admitted request can never hit a mid-generation out-of-pages
  (reservations are the invariant: ``reserved <= available`` always).
  Pages freed by early retirement (EOS) return immediately.

* **Prefix cache** — full prompt pages are published into an
  exact-match chain (key = (parent entry, the page's tokens); no
  hash collisions by construction) as READ-ONLY shared pages.  A new
  request whose prompt starts with a cached chain skips prefilling
  those pages entirely: it pins the entries (refcount) and maps them
  into its own table.  At millions-of-users scale most traffic shares
  system prompts, so this multiplies effective KV capacity.

* **Copy-on-write by recompute** — shared pages are never written.
  Cache hits are FULL-page-granular, and a hit is capped so at least
  one prompt token is always prefilled privately; a request that
  diverges mid-page simply misses that page and prefills its own
  private copy, and generated tokens always land in private pages
  (the first decode write position lies past every shared page by
  construction).  Zero-ref entries stay resident and are evicted
  leaf-first in LRU order only under budget pressure.

``paged_config_from_env`` is the ONE env -> paged-geometry contract,
shared by both serve workers, shardcheck's ``_serve_leaves`` footprint
model, and (through the serve workload profiles) the PR 9 admission
gate — a page budget that cannot hold even one max-length request is
a deploy-time SpecError, not a permanent runtime 503.
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

# physical page 0 is the TRASH page: never allocated, the scatter
# target for padding/inactive-row writes in the device kernels (a
# page-table entry of 0 also means "virtual page not yet allocated" —
# such positions are always masked out of attention)
TRASH_PAGE = 0


def pages_for(tokens: int, page_tokens: int) -> int:
    """Pages covering ``tokens`` KV positions (ceil)."""
    return (tokens + page_tokens - 1) // page_tokens


def worst_case_pages(prompt_len: int, max_new: int, page_tokens: int) -> int:
    """Worst-case pages one request can ever WRITE: positions
    ``[0, prompt_len + max_new - 1)`` — the final sampled token is
    returned but its K/V is never written (nothing decodes after
    it)."""
    return pages_for(prompt_len + max_new - 1, page_tokens)


@dataclass
class PagedServeConfig:
    """The env -> paged-serving geometry contract (one source for
    workers, shardcheck, and the admission gate)."""

    page_tokens: int       # KV positions per page
    pages: int             # usable pages (trash page NOT included)
    chunk_tokens: int      # prefill chunk width (the compile width)
    max_len: int           # virtual per-request position cap
    slots: int             # max concurrent decode rows
    prefix_cache: bool     # share read-only prompt pages

    @property
    def pages_per_row(self) -> int:
        """Page-table length per request row."""
        return pages_for(self.max_len, self.page_tokens)

    @property
    def arena_pages(self) -> int:
        """Physical arena size: usable pages + the trash page."""
        return self.pages + 1


def paged_config_from_env(env) -> Optional[PagedServeConfig]:
    """Derive the paged-serving geometry from a task env; ``None``
    when ``KV_PAGE_TOKENS=0`` selects the legacy slot pool.  Raises
    ``SpecError`` for a geometry that cannot serve (so admission and
    CI reject the spec and a worker fails deploy loudly)."""
    from dcos_commons_tpu.specification.specs import SpecError

    page_tokens = int(env.get("KV_PAGE_TOKENS") or "16")
    if page_tokens <= 0:
        return None
    max_len = int(env.get("MAX_LEN", "256"))
    # unset SERVE_BATCH means a bare/dev launch; fall back to one
    # slot rather than the deploy default 8 (see options.json
    # serving.batch description)
    # sdklint: disable=config-default-drift — dev fallback
    batch = int(env.get("SERVE_BATCH", "1"))
    slots = int(env.get("SERVE_SLOTS") or 0) or batch
    # default budget = full residency for every row (NO overcommit:
    # byte-identical to the slot pool it replaces); operators lower
    # KV_PAGES below slots x pages_per_row to overcommit on the mean
    # request, or raise SERVE_SLOTS at fixed KV_PAGES for free
    # concurrency on short traffic
    per_row = pages_for(max_len, page_tokens)
    pages = int(env.get("KV_PAGES") or 0) or slots * per_row
    chunk = int(env.get("PREFILL_CHUNK_TOKENS") or "64")
    if chunk <= 0:
        raise SpecError(
            f"PREFILL_CHUNK_TOKENS must be >= 1, got {chunk}"
        )
    need_one = pages_for(max_len - 1, page_tokens)
    if pages < need_one:
        raise SpecError(
            f"KV page budget overcommitted: {pages} pages x "
            f"{page_tokens} tokens cannot hold one MAX_LEN={max_len} "
            f"request ({need_one} pages worst-case) — raise "
            f"serving.kv_pages or lower MAX_LEN"
        )
    prefix = (env.get("PREFIX_CACHE", "1") or "1") not in ("0", "false")
    return PagedServeConfig(
        page_tokens=page_tokens, pages=pages, chunk_tokens=chunk,
        max_len=max_len, slots=slots, prefix_cache=prefix,
    )


class _PrefixEntry:
    """One cached read-only prompt page in the exact-match chain."""

    __slots__ = ("eid", "key", "page", "refs", "children")

    def __init__(self, eid: int, key: tuple, page: int):
        self.eid = eid
        self.key = key          # (parent_eid, page-token tuple)
        self.page = page
        self.refs = 0           # active requests reading this page
        self.children = 0       # resident entries chained below


class Admission:
    """The allocator's answer to one admitted request: the pinned
    prefix-chain entries plus the reservation the request draws its
    private pages from."""

    __slots__ = ("matched", "reserve_left", "chain_tail", "chain_open")

    def __init__(self, matched: List[_PrefixEntry], need: int):
        self.matched = matched
        self.reserve_left = need     # un-allocated reservation remainder
        # registration chains onto the last matched entry; a register
        # that finds its key already published closes the chain (the
        # canonical entry belongs to another request)
        self.chain_tail: Optional[_PrefixEntry] = (
            matched[-1] if matched else None
        )
        self.chain_open = True

    @property
    def cached_pages(self) -> int:
        return len(self.matched)


class PageAllocator:
    """Free-list page allocator + prefix cache + admission budget.

    Single-threaded by contract: every call happens on the engine's
    loop thread (or under the engine's cv for stats) — the same
    discipline as the engine's other bookkeeping.  All page ids are
    in ``[1, pages]``; 0 is the trash page and is never owned.

    Core invariant (the budget soundness the property tests hold):
    ``reserved <= available()`` at every step, where ``available`` is
    free pages plus evictable zero-ref cache leaves — so an alloc
    drawn from a reservation can NEVER fail mid-generation.
    """

    def __init__(self, pages: int, page_tokens: int,
                 prefix_cache: bool = True):
        if pages < 1:
            raise ValueError(f"page arena needs >= 1 page, got {pages}")
        if page_tokens < 1:
            raise ValueError(
                f"pages need >= 1 token, got {page_tokens}"
            )
        self.pages_total = pages
        self.page_tokens = page_tokens
        self._prefix_enabled = prefix_cache
        self._free: List[int] = list(range(pages, 0, -1))  # pop -> 1
        self._free_set = set(self._free)
        self._reserved = 0
        self._entries: Dict[tuple, _PrefixEntry] = {}
        self._by_id: Dict[int, _PrefixEntry] = {}
        self._cache_pages = set()  # pages owned by cache entries
        self._lru: "OrderedDict[int, _PrefixEntry]" = OrderedDict()
        # zero-ref entries: ALL reclaimable.  Matching pins whole
        # prefix chains (root-first) and retire unpins them whole, so
        # refcounts are monotone down a chain — a zero-ref entry's
        # entire subtree is zero-ref and leaf-first eviction reaches
        # it transitively.  The LRU holds only the current leaves;
        # this counter is the admission-budget view
        self._zero_refs = 0
        self._next_eid = 1
        # telemetry
        self.prefix_lookups = 0    # prompt pages eligible for a hit
        self.prefix_hits = 0       # prompt pages served from cache
        self.evictions = 0

    def reset(self) -> None:
        """Drop every ownership and cache entry (the engine's
        fail-all path: all admissions died with their groups and the
        arena's contents are no longer trustworthy).  Telemetry
        counters survive — a reset is not a statistics amnesty."""
        self._free = list(range(self.pages_total, 0, -1))
        self._free_set = set(self._free)
        self._reserved = 0
        self._entries.clear()
        self._by_id.clear()
        self._cache_pages.clear()
        self._lru.clear()
        self._zero_refs = 0

    # -- budget ------------------------------------------------------

    def available(self) -> int:
        """Pages an admission may draw on: free + zero-ref cache
        entries (all transitively evictable, leaf-first — see
        ``_zero_refs``)."""
        return len(self._free) + self._zero_refs

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def cached_pages(self) -> int:
        """Resident prefix-cache pages (pinned + reclaimable)."""
        return len(self._by_id)

    @property
    def reclaimable_pages(self) -> int:
        return self._zero_refs

    @property
    def reserved_pages(self) -> int:
        return self._reserved

    def _match_and_need(self, prompt: Sequence[int], max_new: int):
        """The ONE admission formula (shared by ``admit`` and
        ``would_admit`` so the budget decision and the 503 timeout
        classification can never drift): match the prefix chain and
        compute (matched entries, lookup cap, worst-case private-page
        need, budget charge incl. pins of zero-ref entries).  The hit
        is capped so >= 1 prompt token is always prefilled privately:
        the model output at the LAST prompt position is what samples
        the first token — a fully-cached prompt still needs that
        forward pass."""
        plen = len(prompt)
        p = self.page_tokens
        limit = (plen - 1) // p
        matched: List[_PrefixEntry] = []
        if self._prefix_enabled and limit > 0:
            parent_eid = 0
            for i in range(limit):
                key = (parent_eid, tuple(prompt[i * p:(i + 1) * p]))
                entry = self._entries.get(key)
                if entry is None:
                    break
                matched.append(entry)
                parent_eid = entry.eid
        need = worst_case_pages(plen, max_new, p) - len(matched)
        # pinning a zero-ref entry removes it from ``available``, so
        # the admission check must charge for those pins too
        charge = need + sum(1 for e in matched if e.refs == 0)
        return matched, limit, need, charge

    def admit(
        self, prompt: Sequence[int], max_new: int,
    ) -> Optional[Admission]:
        """Transactional admission: match the prefix cache, compute
        the worst-case private-page need, and admit only if it fits.

        Returns ``None`` (leave the request queued, nothing mutated)
        when the budget cannot cover it.  On success the matched
        entries are PINNED and the need RESERVED (an admission must
        never push ``reserved`` past ``available``)."""
        matched, limit, need, charge = self._match_and_need(
            prompt, max_new
        )
        if charge + self._reserved > self.available():
            return None
        # count the hit telemetry only for ADMITTED requests ("nothing
        # mutated" on the None return): a budget-blocked head is
        # re-attempted every engine tick, and counting those retries
        # would drown prefix_cache_hit_rate in retry noise exactly
        # when the arena is saturated
        if self._prefix_enabled and limit > 0:
            self.prefix_lookups += limit
            self.prefix_hits += len(matched)
        for entry in matched:
            self._pin(entry)
        self._reserved += need
        return Admission(matched, need)

    def would_admit(self, prompt: Sequence[int], max_new: int) -> bool:
        """The admission check WITHOUT side effects (the submit-path
        timeout uses it to name the blocking resource)."""
        _, _, _, charge = self._match_and_need(prompt, max_new)
        return charge + self._reserved <= self.available()

    # -- page movement -----------------------------------------------

    def alloc(self, admission: Admission) -> int:
        """Hand one page to an admitted request, drawn from its
        reservation (evicting a zero-ref cache leaf if the free list
        is dry).  A reservation underflow or an empty arena here is an
        ENGINE bug — the admission check exists to make it
        impossible — so it raises instead of limping."""
        if admission.reserve_left <= 0:
            raise RuntimeError(
                "page alloc past the admission's worst-case reservation"
            )
        if not self._free:
            self._evict_one()
        page = self._free.pop()
        self._free_set.discard(page)
        admission.reserve_left -= 1
        self._reserved -= 1
        return page

    def free_page(self, page: int) -> None:
        """Return a PRIVATE page (double-free and trash/cache-page
        frees raise: each is a table-corruption bug upstream)."""
        if page == TRASH_PAGE or not 1 <= page <= self.pages_total:
            raise RuntimeError(f"freeing invalid page {page}")
        if page in self._free_set:
            raise RuntimeError(f"double free of page {page}")
        if page in self._cache_pages:
            raise RuntimeError(
                f"freeing page {page} owned by the prefix cache"
            )
        self._free.append(page)
        self._free_set.add(page)

    def retire(self, admission: Admission,
               private_pages: Sequence[int]) -> None:
        """Release everything one request held: the un-consumed
        reservation, its private pages, and its pins (matched AND
        self-registered entries — a registered page must stay pinned
        while its registrant can still gather from it)."""
        self._reserved -= admission.reserve_left
        admission.reserve_left = 0
        for page in private_pages:
            self.free_page(page)
        for entry in admission.matched:
            self._unpin(entry)

    # -- prefix cache ------------------------------------------------

    def register(
        self, admission: Admission, page_tokens: Tuple[int, ...],
        page: int,
    ) -> bool:
        """Publish one fully-prefilled PRIVATE prompt page into the
        cache, chained onto the request's current tail.  Ownership of
        ``page`` transfers to the cache; the registrant keeps a pin
        until retire (``admission.matched`` grows the new entry).

        Returns False (page stays private) when the chain is closed
        or the key already exists — a concurrent identical prompt
        published first; this request keeps its duplicate private and
        the canonical entry serves future hits.  Once closed, the
        chain stays closed: deeper pages cannot chain onto another
        request's entry without pinning machinery admission never
        budgeted for."""
        if not self._prefix_enabled or not admission.chain_open:
            return False
        if len(page_tokens) != self.page_tokens:
            raise RuntimeError(
                f"registering a partial page ({len(page_tokens)} of "
                f"{self.page_tokens} tokens)"
            )
        parent = admission.chain_tail
        key = ((parent.eid if parent else 0), tuple(page_tokens))
        if key in self._entries:
            admission.chain_open = False
            return False
        entry = _PrefixEntry(self._next_eid, key, page)
        self._next_eid += 1
        entry.refs = 1  # the registrant's pin, released at retire
        if parent is not None:
            # parent is pinned by this request (matched or registered
            # earlier in this chain): refs >= 1, so it cannot be in
            # the LRU and gaining a child never shrinks ``available``
            parent.children += 1
        self._entries[key] = entry
        self._by_id[entry.eid] = entry
        self._cache_pages.add(page)
        admission.matched.append(entry)
        admission.chain_tail = entry
        return True

    def _pin(self, entry: _PrefixEntry) -> None:
        if entry.refs == 0:
            self._zero_refs -= 1
        entry.refs += 1
        self._lru.pop(entry.eid, None)

    def _unpin(self, entry: _PrefixEntry) -> None:
        entry.refs -= 1
        if entry.refs < 0:
            raise RuntimeError(f"refcount underflow on entry {entry.eid}")
        if entry.refs == 0:
            self._zero_refs += 1
            if entry.children == 0:
                self._lru[entry.eid] = entry
                self._lru.move_to_end(entry.eid)

    def _evict_one(self) -> None:
        if not self._lru:
            raise RuntimeError(
                "page arena empty with nothing evictable (budget "
                "invariant violated)"
            )
        _eid, entry = self._lru.popitem(last=False)  # oldest leaf
        del self._entries[entry.key]
        del self._by_id[entry.eid]
        self._cache_pages.discard(entry.page)
        self._zero_refs -= 1  # lru membership implies refs == 0
        parent_eid = entry.key[0]
        if parent_eid:
            parent = self._by_id.get(parent_eid)
            if parent is not None:
                parent.children -= 1
                if parent.refs == 0 and parent.children == 0:
                    self._lru[parent.eid] = parent
        self._free.append(entry.page)
        self._free_set.add(entry.page)
        self.evictions += 1

    # -- introspection (tests + stats) -------------------------------

    def check_invariants(self, private_pages: Sequence[int] = ()) -> None:
        """Conservation + budget soundness; the property tests call
        this after every op.  ``private_pages``: every page currently
        owned by live requests (the engine's tables)."""
        cached = {e.page for e in self._by_id.values()}
        private = list(private_pages)
        if len(cached) != len(self._by_id):
            raise AssertionError("two cache entries share a page")
        if len(set(private)) != len(private):
            raise AssertionError("two requests own the same page")
        if set(private) & cached:
            raise AssertionError("a private page is also cache-owned")
        if set(private) & self._free_set or cached & self._free_set:
            raise AssertionError("an owned page is on the free list")
        total = len(self._free) + len(cached) + len(private)
        if total != self.pages_total:
            raise AssertionError(
                f"page conservation broken: {len(self._free)} free + "
                f"{len(cached)} cached + {len(private)} private != "
                f"{self.pages_total}"
            )
        if self._reserved < 0:
            raise AssertionError("negative reservation")
        if self._reserved > self.available():
            raise AssertionError(
                f"reserved {self._reserved} > available "
                f"{self.available()}: an admitted request can OOM"
            )
        zero = 0
        for entry in self._by_id.values():
            zero += entry.refs == 0
            evictable = entry.refs == 0 and entry.children == 0
            if evictable != (entry.eid in self._lru):
                raise AssertionError(
                    f"entry {entry.eid} LRU membership inconsistent "
                    f"(refs={entry.refs}, children={entry.children})"
                )
            parent_eid = entry.key[0]
            if parent_eid and entry.refs > 0:
                parent = self._by_id.get(parent_eid)
                if parent is None or parent.refs <= 0:
                    raise AssertionError(
                        f"pinned entry {entry.eid} has an unpinned/"
                        "evicted parent (chain-pin monotonicity broken)"
                    )
        if zero != self._zero_refs:
            raise AssertionError(
                f"zero-ref count drifted: {self._zero_refs} tracked, "
                f"{zero} actual"
            )

    def stats(self) -> dict:
        lookups = self.prefix_lookups
        return {
            "kv_pages_total": self.pages_total,
            "kv_pages_free": len(self._free),
            "kv_pages_cached": len(self._by_id),
            # all zero-ref entries, matching the admission view — not
            # just the current LRU leaves (a zero-ref CHAIN is
            # transitively evictable, and the gauge must agree with
            # what available() would actually hand an admission)
            "kv_pages_reclaimable": self._zero_refs,
            "kv_pages_reserved": self._reserved,
            "prefix_cache_hits": self.prefix_hits,
            "prefix_cache_lookups": lookups,
            "prefix_cache_evictions": self.evictions,
            "prefix_cache_hit_rate": round(
                self.prefix_hits / lookups, 4
            ) if lookups else 0.0,
        }
