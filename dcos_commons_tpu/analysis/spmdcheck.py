"""spmdcheck: whole-program SPMD collective-safety analysis.

The production failure this hunts is CROSS-HOST DIVERGENCE: a
multi-host pjit gang is one SPMD program replicated per process, and
every process must issue the *same sequence of collectives* (psum,
ppermute, all_gather, all_to_all, broadcast, rendezvous).  One host
taking a branch the others don't — because it branched on its own
rank, its own disk, its own clock, or an unordered container — makes
the gang's collective schedules disagree, and the slice deadlocks at
the next collective with no stack trace worth reading.  That bug
class is invisible to single-file lint (PR 2's sdklint) because the
collective is usually three calls away from the divergent branch, so
this pass is interprocedural: it builds a per-function collective
summary, propagates it over the call graph to a fixpoint, and then
checks five named hazard rules at the AST level.

Rules (each suppressible with ``# sdklint: disable=<rule>`` and
absorbable by the shared ``.sdklint-baseline.json``):

- ``spmd-host-branch``: a collective reachable under an ``if``/
  ``while`` whose test depends on a host-identity value
  (``jax.process_index()``, ``worker_id``/``rank``, per-host env,
  hostname, urandom, wall clock).
- ``spmd-traced-cond``: a collective under data-dependent control
  flow on a device-varying value (``lax.axis_index`` derived) —
  Python ``if`` or ``lax.cond``/``lax.switch`` branches.
- ``spmd-unknown-axis``: a collective names a mesh axis that appears
  in no ``Mesh``/``MeshSpec``/axis-name vocabulary of the tree.
- ``spmd-unordered-iter``: a collective schedule built by iterating a
  ``set``/``frozenset`` or ``os.environ`` — iteration order is not
  guaranteed identical across hosts.
- ``spmd-per-host-trip-count``: a loop that executes collectives (or
  jit-compiled mesh programs) whose trip count derives from a
  per-host value (checkpoint restore, ``jax.local_devices()``,
  ``process_index``, clock, urandom).

Scope: ``dcos_commons_tpu/{parallel,models,ops,utils,storage}`` and
``frameworks/jax`` — the layers that run inside or drive the SPMD
data plane.  Findings reuse the sdklint ``Finding``/``Suppressions``
machinery so the CLI, baseline, and gate treatment are identical.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from dcos_commons_tpu.analysis.linter import (
    Finding,
    LintResult,
    Suppressions,
)

# directories (relative to the repo root) the analyzer walks
SPMD_SUBDIRS = (
    "dcos_commons_tpu/parallel",
    "dcos_commons_tpu/models",
    "dcos_commons_tpu/ops",
    "dcos_commons_tpu/utils",
    "dcos_commons_tpu/storage",
    "frameworks/jax",
)

# the mesh-axis collectives (axis name = 2nd arg / axis_name kwarg)
LAX_COLLECTIVES = {
    "psum", "pmean", "pmax", "pmin", "ppermute", "pshuffle",
    "all_gather", "all_to_all", "psum_scatter",
}
# explicit cross-process synchronization points: every process of the
# gang must execute these the same number of times in the same order
COLLECTIVE_OPS = LAX_COLLECTIVES | {
    "broadcast_one_to_all", "process_allgather", "sync_global_devices",
    "assert_equal", "initialize",  # jax.distributed.initialize rendezvous
}
# results of these are gang-uniform by construction: consuming them
# does NOT taint, and assigning from them CLEANSES a tainted name
UNIFORMIZERS = {"broadcast_one_to_all", "process_allgather", "psum",
                "pmean", "pmax", "pmin", "all_gather"}
# producers of mesh programs: calling their result executes whatever
# collectives XLA/GSPMD inserts, so loops driving them are schedules
TRACER_ENTRY_POINTS = {"jit", "pjit", "shard_map", "pmap", "xmap"}

# host-identity taint seeds ------------------------------------------------
_HOST_CALLS = {
    "process_index", "getpid", "gethostname", "urandom", "uuid1",
    "uuid4", "time", "monotonic", "perf_counter", "time_ns",
}
# per-host but NOT host-identity (don't flag branches, do flag trip
# counts): local device topology and local disk state
_PER_HOST_CALLS = _HOST_CALLS | {
    "local_devices", "local_device_count", "restore_checkpoint",
    "latest_step",
}
# subscript/attribute keys that carry host identity through dicts
# (the scheduler's env contract: TPU_WORKER_ID differs per host,
# TPU_WORKER_COUNT etc. are gang-uniform)
_HOST_KEYS = {"worker_id", "process_id", "host_id", "rank", "hostname"}
_HOST_ENV_MARKERS = ("WORKER_ID", "PROCESS_ID", "HOSTNAME", "HOST_ID",
                     "NODE_ID", "RANK")


def _call_name(node: ast.Call) -> str:
    """Rightmost name of the called expression: ``a.b.c(...)`` -> c."""
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _collective_axis(node: ast.Call) -> Optional[str]:
    """Literal axis name of a collective call, if statically visible."""
    for kw in node.keywords:
        if kw.arg in ("axis_name", "axis"):
            return _const_str(kw.value)
    if len(node.args) >= 2:
        return _const_str(node.args[1])
    return None


@dataclass
class FunctionSummary:
    """What one function may do, transitively, to the gang."""

    qualname: str
    file: str
    lineno: int
    # (op, axis-or-None) pairs this function may execute
    collectives: Set[Tuple[str, Optional[str]]] = field(default_factory=set)
    # resolved callee keys + unresolved simple names
    calls: Set[str] = field(default_factory=set)
    # builds a jit/shard_map program (calling its RESULT runs a mesh
    # program, i.e. collectives from the runtime's point of view)
    traces: bool = False

    @property
    def may_collect(self) -> bool:
        return bool(self.collectives)


class ProgramSummary:
    """All function summaries of the scanned tree + the call graph
    fixpoint.  Call resolution is name-based: imports map simple names
    to module-qualified keys; a simple name defined in exactly one
    scanned module resolves across files."""

    def __init__(self) -> None:
        self.functions: Dict[str, FunctionSummary] = {}
        # simple name -> set of summary keys carrying that name
        self.by_name: Dict[str, Set[str]] = {}
        # axis-name vocabulary harvested from Mesh(...)/MeshSpec/axis
        # parameter defaults across the tree
        self.axis_vocab: Set[str] = set()

    def add(self, key: str, summary: FunctionSummary) -> None:
        self.functions[key] = summary
        simple = key.rsplit(".", 1)[-1]
        self.by_name.setdefault(simple, set()).add(key)

    def resolve(self, name: str) -> List[FunctionSummary]:
        """Summaries a call to ``name`` may land in."""
        if name in self.functions:
            return [self.functions[name]]
        keys = self.by_name.get(name.rsplit(".", 1)[-1], ())
        return [self.functions[k] for k in keys]

    def propagate(self) -> None:
        """Union callee collectives/traces into callers to fixpoint."""
        changed = True
        rounds = 0
        while changed and rounds < 50:
            changed = False
            rounds += 1
            for summary in self.functions.values():
                for callee_name in summary.calls:
                    for callee in self.resolve(callee_name):
                        if callee is summary:
                            continue
                        if not callee.collectives <= summary.collectives:
                            summary.collectives |= callee.collectives
                            changed = True
                        if callee.traces and not summary.traces:
                            summary.traces = True
                            changed = True

    def call_effects(
        self, call: ast.Call
    ) -> Tuple[Set[Tuple[str, Optional[str]]], bool]:
        """(collectives, traces) a call site may trigger."""
        name = _call_name(call)
        if not name:
            return set(), False
        if name in COLLECTIVE_OPS:
            return {(name, _collective_axis(call))}, False
        if name in TRACER_ENTRY_POINTS:
            return set(), True
        out: Set[Tuple[str, Optional[str]]] = set()
        traces = False
        for summary in self.resolve(name):
            out |= summary.collectives
            traces = traces or summary.traces
        return out, traces


# -- pass 1: build summaries ------------------------------------------------


class _SummaryBuilder(ast.NodeVisitor):
    """Collects one file's function summaries + axis vocabulary.

    Nested functions fold into their enclosing def's summary: calling
    a factory (or the closure it returns) may run the closure's
    collectives, and that over-approximation is the safe direction
    for divergence hazards.
    """

    def __init__(self, rel: str, program: ProgramSummary):
        self.rel = rel
        self.module = rel[:-3].replace("/", ".")
        self.program = program
        self._stack: List[FunctionSummary] = []

    # vocabulary ------------------------------------------------------

    def _harvest_vocab(self, node: ast.Call) -> None:
        name = _call_name(node)
        if name == "Mesh" and len(node.args) >= 2:
            names_arg = node.args[1]
            if isinstance(names_arg, (ast.Tuple, ast.List)):
                for elt in names_arg.elts:
                    axis = _const_str(elt)
                    if axis:
                        self.program.axis_vocab.add(axis)
        elif name == "MeshSpec":
            for kw in node.keywords:
                if kw.arg:
                    self.program.axis_vocab.add(kw.arg)

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        # MeshSpec-style axis dataclasses: field names are axes
        if any(
            isinstance(d, ast.Name) and d.id == "dataclass"
            or isinstance(d, ast.Call) and _call_name(d) == "dataclass"
            for d in node.decorator_list
        ) and "mesh" in node.name.lower():
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) and \
                        isinstance(stmt.target, ast.Name):
                    self.program.axis_vocab.add(stmt.target.id)
        self.generic_visit(node)

    # functions -------------------------------------------------------

    def _enter(self, node) -> None:
        if self._stack:
            # nested def: keep folding into the enclosing summary
            self._harvest_defaults(node)
            for stmt in node.body:
                self.visit(stmt)
            return
        summary = FunctionSummary(
            qualname=f"{self.module}.{node.name}",
            file=self.rel,
            lineno=node.lineno,
        )
        for decorator in node.decorator_list:
            for sub in ast.walk(decorator):
                if (isinstance(sub, ast.Name)
                        and sub.id in TRACER_ENTRY_POINTS) or (
                        isinstance(sub, ast.Attribute)
                        and sub.attr in TRACER_ENTRY_POINTS):
                    summary.traces = True
        self._stack.append(summary)
        self._harvest_defaults(node)
        for stmt in node.body:
            self.visit(stmt)
        self._stack.pop()
        self.program.add(summary.qualname, summary)

    def _harvest_defaults(self, node) -> None:
        """axis_name="sp" parameter defaults feed the vocabulary."""
        args = node.args
        pos = args.posonlyargs + args.args
        for arg, default in zip(pos[len(pos) - len(args.defaults):],
                                args.defaults):
            if arg.arg in ("axis_name", "axis") :
                axis = _const_str(default)
                if axis:
                    self.program.axis_vocab.add(axis)
        for arg, default in zip(args.kwonlyargs, args.kw_defaults):
            if default is not None and arg.arg in ("axis_name", "axis"):
                axis = _const_str(default)
                if axis:
                    self.program.axis_vocab.add(axis)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._enter(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._enter(node)

    def visit_Call(self, node: ast.Call) -> None:
        self._harvest_vocab(node)
        name = _call_name(node)
        if self._stack and name:
            summary = self._stack[-1]
            if name in COLLECTIVE_OPS:
                summary.collectives.add((name, _collective_axis(node)))
            elif name in TRACER_ENTRY_POINTS:
                summary.traces = True
            else:
                summary.calls.add(name)
        self.generic_visit(node)


def build_summary(files: Iterable[Tuple[str, str, str]]) -> ProgramSummary:
    """files: (abs_path, rel_path, source) triples."""
    program = ProgramSummary()
    for _, rel, source in files:
        try:
            tree = ast.parse(source)
        except SyntaxError:
            continue
        _SummaryBuilder(rel, program).visit(tree)
    program.propagate()
    return program


# -- taint engine -----------------------------------------------------------


class _Taint:
    """Flow-ordered name taint for one function body.

    Three colors: ``host`` (host-identity: rank/pid/clock/urandom),
    ``perhost`` (host-local but not identity: checkpoint stamp, local
    device count — superset of host), ``varying`` (device-varying:
    lax.axis_index derived).  Assignment from a uniformizing
    collective cleanses all three.
    """

    def __init__(self, program: ProgramSummary):
        self.program = program
        self.host: Set[str] = set()
        self.perhost: Set[str] = set()
        self.varying: Set[str] = set()

    # -- expression coloring -----------------------------------------

    def _env_key_is_host(self, call: ast.Call) -> bool:
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            key = _const_str(arg)
            if key and any(m in key.upper() for m in _HOST_ENV_MARKERS):
                return True
        return False

    def expr_colors(self, node: ast.AST) -> Set[str]:
        colors: Set[str] = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name):
                if sub.id in self.host:
                    colors |= {"host", "perhost"}
                if sub.id in self.perhost:
                    colors.add("perhost")
                if sub.id in self.varying:
                    colors.add("varying")
            elif isinstance(sub, ast.Call):
                name = _call_name(sub)
                if name in UNIFORMIZERS:
                    # a uniformizer's ARGUMENTS don't leak through it
                    return colors
                if name == "axis_index":
                    colors.add("varying")
                if name in _HOST_CALLS:
                    colors |= {"host", "perhost"}
                elif name in _PER_HOST_CALLS:
                    colors.add("perhost")
                elif name in ("get", "getenv") and self._env_key_is_host(sub):
                    colors |= {"host", "perhost"}
            elif isinstance(sub, ast.Subscript):
                key = _const_str(sub.slice)
                if key in _HOST_KEYS:
                    colors |= {"host", "perhost"}
            elif isinstance(sub, ast.Attribute):
                if sub.attr in _HOST_KEYS:
                    colors |= {"host", "perhost"}
        return colors

    def _is_uniformizer_result(self, value: ast.AST) -> bool:
        for sub in ast.walk(value):
            if isinstance(sub, ast.Call) and \
                    _call_name(sub) in UNIFORMIZERS:
                return True
        return False

    # -- statement-order updates -------------------------------------

    def _target_names(self, target: ast.AST) -> List[str]:
        out = []
        for sub in ast.walk(target):
            if isinstance(sub, ast.Name):
                out.append(sub.id)
        return out

    def assign(self, targets: Sequence[ast.AST], value: ast.AST) -> None:
        if isinstance(value, (ast.Dict, ast.DictComp)):
            # dict literals are not tainted wholesale: consumers are
            # discriminated per key at the subscript (the env-contract
            # dict mixes per-host worker_id with gang-uniform values)
            return
        colors = self.expr_colors(value)
        cleanse = self._is_uniformizer_result(value) and not colors
        names = [n for t in targets for n in self._target_names(t)]
        for name in names:
            if cleanse:
                self.host.discard(name)
                self.perhost.discard(name)
                self.varying.discard(name)
                continue
            if "host" in colors:
                self.host.add(name)
            if "perhost" in colors:
                self.perhost.add(name)
            if "varying" in colors:
                self.varying.add(name)
            if not colors:
                self.host.discard(name)
                self.perhost.discard(name)
                self.varying.discard(name)


# -- pass 2: the rules ------------------------------------------------------


class SpmdRule:
    id = ""
    description = ""

    def check(self, ctx: "SpmdContext") -> List[Finding]:
        raise NotImplementedError


class SpmdContext:
    """One file + the whole-program summary, pre-chewed for rules."""

    def __init__(self, rel: str, tree: ast.AST, program: ProgramSummary):
        self.rel = rel
        self.tree = tree
        self.program = program

    def finding(self, node: ast.AST, rule: str, message: str) -> Finding:
        return Finding(self.rel, getattr(node, "lineno", 1), rule, message)

    def may_collect(self, node: ast.AST) -> Set[Tuple[str, Optional[str]]]:
        """All collectives any call inside ``node`` may execute."""
        out: Set[Tuple[str, Optional[str]]] = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                ops, _ = self.program.call_effects(sub)
                out |= ops
        return out

    def may_run_mesh_program(self, node: ast.AST,
                             traced_names: Set[str]) -> bool:
        """True when ``node`` may execute collectives OR call a
        jit/shard_map-produced function (implicit GSPMD collectives)."""
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            ops, traces = self.program.call_effects(sub)
            if ops or traces:
                return True
            func = sub.func
            if isinstance(func, ast.Name) and func.id in traced_names:
                return True
        return False

    def functions(self):
        """Every def in the file, plus the module body as one
        pseudo-function — a worker driver script whose collective loop
        sits at top level (no main() wrapper) is the same hazard."""
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield node
        toplevel = [
            stmt for stmt in self.tree.body
            if not isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef,
                                     ast.ClassDef))
        ]
        if toplevel:
            shell = ast.parse("def f():\n    pass").body[0]
            shell.name = "<module>"
            shell.body = toplevel
            yield shell


def _walk_statements(body: Sequence[ast.stmt], taint: _Taint,
                     traced_names: Set[str], program: ProgramSummary,
                     visit_stmt) -> None:
    """Source-order statement walk maintaining taint + the set of
    names bound to jit/shard_map program objects."""
    for stmt in body:
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (
                stmt.targets if isinstance(stmt, ast.Assign)
                else [stmt.target]
            )
            value = stmt.value
            if value is not None:
                for sub in ast.walk(value):
                    if isinstance(sub, ast.Call):
                        _, traces = program.call_effects(sub)
                        if traces:
                            for t in targets:
                                if isinstance(t, ast.Name):
                                    traced_names.add(t.id)
                taint.assign(targets, value)
        visit_stmt(stmt)
        for child_body in _stmt_bodies(stmt):
            _walk_statements(child_body, taint, traced_names, program,
                             visit_stmt)


def _stmt_bodies(stmt: ast.stmt) -> List[Sequence[ast.stmt]]:
    out = []
    for name in ("body", "orelse", "finalbody"):
        body = getattr(stmt, name, None)
        if body and not isinstance(
            stmt, (ast.FunctionDef, ast.AsyncFunctionDef)
        ):
            out.append(body)
    for handler in getattr(stmt, "handlers", ()):
        out.append(handler.body)
    if isinstance(stmt, ast.For):
        pass  # body already covered above
    return out


class HostBranchRule(SpmdRule):
    """A collective below an ``if``/``while`` whose test carries host
    identity (rank, pid, hostname, urandom, clock, per-host env).  If
    any host takes the branch and another doesn't, their collective
    schedules disagree and the gang deadlocks.  Driver loops where
    leader and followers deliberately meet in the SAME collective
    sequence (broadcast fan-out) are the legitimate annotated
    exception."""

    id = "spmd-host-branch"
    description = "collective reachable under a host-identity branch"

    def check(self, ctx: SpmdContext) -> List[Finding]:
        out = []
        for func in ctx.functions():
            taint = _Taint(ctx.program)
            # parameters named like host identity are tainted (a
            # helper taking `rank` is still a divergence site)
            for arg in func.args.posonlyargs + func.args.args \
                    + func.args.kwonlyargs:
                if arg.arg in _HOST_KEYS:
                    taint.host.add(arg.arg)
            traced: Set[str] = set()

            def visit(stmt, _taint=taint, _out=out, _func=func):
                if isinstance(stmt, (ast.If, ast.While)):
                    if "host" not in _taint.expr_colors(stmt.test):
                        return
                    ops = ctx.may_collect(stmt)
                    if ops:
                        names = sorted({op for op, _ in ops})
                        _out.append(ctx.finding(
                            stmt, self.id,
                            f"collective {'/'.join(names)} reachable "
                            "under a branch on host identity in "
                            f"{_func.name}(); all hosts must issue the "
                            "same collective sequence (annotate driver "
                            "loops that meet in a broadcast)",
                        ))

            _walk_statements(func.body, taint, traced, ctx.program, visit)
        return out


class TracedCondRule(SpmdRule):
    """A collective under control flow on a DEVICE-VARYING value
    (``lax.axis_index`` derived): each mesh position takes its own
    branch, so a collective inside any branch is entered by some
    devices and not others.  Compute per-rank values with masks
    (``jnp.where``, one-hot psum — see pipeline_loss_fn) and keep
    branch bodies collective-free."""

    id = "spmd-traced-cond"
    description = "collective under device-varying lax.cond/if"

    def check(self, ctx: SpmdContext) -> List[Finding]:
        out = []
        for func in ctx.functions():
            taint = _Taint(ctx.program)
            traced: Set[str] = set()

            def visit(stmt, _taint=taint, _out=out, _func=func):
                # python control flow on a varying value
                if isinstance(stmt, (ast.If, ast.While)):
                    if "varying" in _taint.expr_colors(stmt.test):
                        ops = ctx.may_collect(stmt)
                        if ops:
                            names = sorted({op for op, _ in ops})
                            _out.append(ctx.finding(
                                stmt, self.id,
                                f"collective {'/'.join(names)} under "
                                "control flow on a device-varying value "
                                f"in {_func.name}(); use a mask/where "
                                "instead of a branch",
                            ))
                # lax.cond / lax.switch with a varying predicate and a
                # collective-bearing branch function.  Only simple
                # statements are scanned here — compound bodies reach
                # this visitor statement by statement already.
                simple = isinstance(stmt, (
                    ast.Assign, ast.AnnAssign, ast.AugAssign,
                    ast.Expr, ast.Return,
                ))
                for sub in ast.walk(stmt) if simple else ():
                    if not isinstance(sub, ast.Call):
                        continue
                    if _call_name(sub) not in ("cond", "switch"):
                        continue
                    if not sub.args:
                        continue
                    if "varying" not in _taint.expr_colors(sub.args[0]):
                        continue
                    for branch in sub.args[1:]:
                        ops = self._branch_collectives(ctx, branch)
                        if ops:
                            names = sorted({op for op, _ in ops})
                            _out.append(ctx.finding(
                                sub, self.id,
                                f"lax.cond/switch branch runs collective "
                                f"{'/'.join(names)} under a device-"
                                f"varying predicate in {_func.name}(); "
                                "ranks will take different branches",
                            ))
                            break

            _walk_statements(func.body, taint, traced, ctx.program, visit)
        return out

    @staticmethod
    def _branch_collectives(ctx: SpmdContext, branch: ast.AST):
        if isinstance(branch, ast.Lambda):
            return ctx.may_collect(branch.body)
        if isinstance(branch, ast.Name):
            out = set()
            for summary in ctx.program.resolve(branch.id):
                out |= summary.collectives
            return out
        return ctx.may_collect(branch)


class UnknownAxisRule(SpmdRule):
    """A collective's literal axis name must exist in the tree's mesh
    vocabulary (``Mesh((...), names)`` tuples, ``MeshSpec`` axes,
    ``axis_name=`` parameter defaults).  An axis absent from every
    mesh raises at trace time in the best case — and silently reduces
    over the wrong group if a mesh elsewhere happens to define it."""

    id = "spmd-unknown-axis"
    description = "collective axis name absent from the mesh vocabulary"

    def check(self, ctx: SpmdContext) -> List[Finding]:
        vocab = ctx.program.axis_vocab
        if not vocab:
            return []  # no meshes in scope: nothing to judge against
        out = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node)
            if name not in LAX_COLLECTIVES:
                continue
            axis = _collective_axis(node)
            if axis is not None and axis not in vocab:
                out.append(ctx.finding(
                    node, self.id,
                    f"{name} over axis {axis!r}, which no Mesh/MeshSpec/"
                    f"axis default in the tree declares "
                    f"(known: {', '.join(sorted(vocab))})",
                ))
        return out


class UnorderedIterRule(SpmdRule):
    """A collective schedule built by iterating a ``set``/
    ``frozenset`` or ``os.environ``: set iteration order depends on
    per-process hash seeding, so two hosts iterating the "same" set
    can build different permute tables or issue collectives in
    different orders — the textbook silent-divergence bug.  Iterate a
    ``sorted(...)`` copy instead."""

    id = "spmd-unordered-iter"
    description = "collective schedule iterates a set/os.environ"

    @staticmethod
    def _is_unordered(iter_node: ast.AST) -> bool:
        node = iter_node
        # x.keys()/values()/items() — look through to the receiver
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in ("keys", "values", "items"):
            node = node.func.value
        if isinstance(node, ast.Set):
            return True
        if isinstance(node, ast.Call) and _call_name(node) in (
            "set", "frozenset"
        ):
            return True
        if isinstance(node, ast.Attribute) and node.attr == "environ":
            return True
        if isinstance(node, ast.BinOp) and isinstance(
            node.op, (ast.BitOr, ast.BitAnd, ast.Sub)
        ):
            # set algebra: a | b, a & b, a - b
            return UnorderedIterRule._is_unordered(node.left) or \
                UnorderedIterRule._is_unordered(node.right)
        return False

    def check(self, ctx: SpmdContext) -> List[Finding]:
        out = []
        for func in ctx.functions():
            # names assigned from comprehensions over unordered iters
            unordered_names: Set[str] = set()
            for node in ast.walk(func):
                if isinstance(node, ast.Assign) and isinstance(
                    node.value, (ast.ListComp, ast.GeneratorExp)
                ):
                    if any(self._is_unordered(gen.iter)
                           for gen in node.value.generators):
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                unordered_names.add(t.id)
            for node in ast.walk(func):
                # a loop over an unordered iterable containing a
                # collective: per-iteration schedule diverges
                if isinstance(node, ast.For) and \
                        self._is_unordered(node.iter):
                    ops = ctx.may_collect(node)
                    if ops:
                        names = sorted({op for op, _ in ops})
                        out.append(ctx.finding(
                            node, self.id,
                            f"collective {'/'.join(names)} issued while "
                            "iterating an unordered set/env mapping in "
                            f"{func.name}(); iteration order differs "
                            "across hosts — iterate sorted(...)",
                        ))
                # an unordered-built name fed into a collective call
                if isinstance(node, ast.Call) and \
                        _call_name(node) in COLLECTIVE_OPS:
                    args = list(node.args) + [
                        kw.value for kw in node.keywords
                    ]
                    for arg in args:
                        for sub in ast.walk(arg):
                            if isinstance(sub, ast.Name) and \
                                    sub.id in unordered_names:
                                out.append(ctx.finding(
                                    node, self.id,
                                    f"{_call_name(node)} consumes "
                                    f"{sub.id!r}, built from an "
                                    "unordered set — the schedule can "
                                    "differ across hosts",
                                ))
                                break
                        else:
                            continue
                        break
        return out


class PerHostTripCountRule(SpmdRule):
    """A loop executing collectives (or jit/shard_map mesh programs)
    whose trip count derives from a PER-HOST value — a checkpoint
    stamp read from local disk, ``jax.local_devices()``, the clock,
    ``process_index``.  If one host runs 99 iterations and its
    neighbor runs 100, the neighbor blocks forever in iteration 100's
    collective.  Agree on the bound first (``process_allgather`` /
    ``broadcast_one_to_all``), then loop."""

    id = "spmd-per-host-trip-count"
    description = "collective loop trip count from a per-host value"

    def check(self, ctx: SpmdContext) -> List[Finding]:
        out = []
        for func in ctx.functions():
            taint = _Taint(ctx.program)
            traced: Set[str] = set()

            def visit(stmt, _taint=taint, _traced=traced, _out=out,
                      _func=func):
                bound: Optional[ast.AST] = None
                if isinstance(stmt, ast.For):
                    bound = stmt.iter
                elif isinstance(stmt, ast.While):
                    bound = stmt.test
                if bound is None:
                    return
                if "perhost" not in _taint.expr_colors(bound):
                    return
                if ctx.may_run_mesh_program(stmt, _traced):
                    _out.append(ctx.finding(
                        stmt, self.id,
                        f"loop in {_func.name}() executes collectives "
                        "but its trip count derives from a per-host "
                        "value; hosts that disagree on the bound "
                        "deadlock — agree via process_allgather/"
                        "broadcast first",
                    ))

            _walk_statements(func.body, taint, traced, ctx.program, visit)
        return out


def all_spmd_rules() -> List[SpmdRule]:
    return [
        HostBranchRule(),
        TracedCondRule(),
        UnknownAxisRule(),
        UnorderedIterRule(),
        PerHostTripCountRule(),
    ]


def spmd_rule_catalog() -> str:
    blocks = []
    for rule in all_spmd_rules():
        doc = " ".join((rule.__doc__ or "").split())
        blocks.append(f"{rule.id}: {rule.description}\n    {doc}")
    return "\n\n".join(blocks)


# -- driver -----------------------------------------------------------------


def _collect_files(root: str,
                   subdirs: Sequence[str]) -> List[Tuple[str, str, str]]:
    out = []
    for sub in subdirs:
        top = os.path.join(root, sub)
        if not os.path.isdir(top):
            continue
        for dirpath, dirs, files in os.walk(top):
            dirs[:] = sorted(d for d in dirs if d != "__pycache__")
            for name in sorted(files):
                if not name.endswith(".py"):
                    continue
                path = os.path.join(dirpath, name)
                with open(path, "r", encoding="utf-8") as f:
                    source = f.read()
                rel = os.path.relpath(path, root).replace(os.sep, "/")
                out.append((path, rel, source))
    return out


def analyze_paths(files: Sequence[Tuple[str, str, str]],
                  rules: Optional[Sequence[SpmdRule]] = None) -> LintResult:
    """Run spmdcheck over pre-read (path, rel, source) triples."""
    program = build_summary(files)
    active = list(rules) if rules is not None else all_spmd_rules()
    result = LintResult()
    for _, rel, source in files:
        try:
            tree = ast.parse(source)
        except SyntaxError:
            continue
        result.files_checked += 1
        ctx = SpmdContext(rel, tree, program)
        suppressions = Suppressions(source.splitlines())
        for rule in active:
            for finding in rule.check(ctx):
                if suppressions.covers(finding):
                    result.suppressed.append(finding)
                else:
                    result.findings.append(finding)
    result.findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return result


def analyze_tree(root: str,
                 subdirs: Sequence[str] = SPMD_SUBDIRS) -> LintResult:
    """Run spmdcheck over the SPMD-relevant subtrees of ``root``."""
    return analyze_paths(_collect_files(root, subdirs))
