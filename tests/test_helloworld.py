"""frameworks/helloworld: the feature-matrix service, one test per YAML.

Reference: frameworks/helloworld — 36 svc YAMLs x 40 integration test
modules are the reference's coverage engine
(frameworks/helloworld/src/main/dist/, frameworks/helloworld/tests/).
Each test here loads the real YAML from frameworks/helloworld/ and
drives it through the sim harness, mirroring the reference's
ServiceTest.java flows for that YAML.
"""

import os

import pytest

from dcos_commons_tpu.offer.inventory import TpuHost
from dcos_commons_tpu.plan.status import Status
from dcos_commons_tpu.scheduler.config import SchedulerConfig
from dcos_commons_tpu.testing import (
    AddHost,
    AdvanceCycles,
    ExpectDeclined,
    ExpectDeploymentComplete,
    ExpectDistinctHosts,
    ExpectLaunchedTasks,
    ExpectNoLaunches,
    ExpectPlanStatus,
    ExpectStepStatus,
    ExpectTaskKilled,
    PlanContinue,
    PlanStart,
    SendTaskFailed,
    SendTaskFinished,
    SendTaskRunning,
    ServiceTestRunner,
)

HELLOWORLD = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "frameworks",
    "helloworld",
)


def load(yaml_name: str) -> str:
    with open(os.path.join(HELLOWORLD, yaml_name), "r", encoding="utf-8") as f:
        return f.read()


def test_svc_default_two_pod_types():
    """svc.yml: hello (volume + health check) then world x2 (two
    volumes, readiness check) deploy serially to completion."""
    runner = ServiceTestRunner(load("svc.yml"))
    runner.run([
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-0-server"),
        SendTaskRunning("hello-0-server"),
        AdvanceCycles(1),
        ExpectLaunchedTasks("world-0-server"),
        # readiness check declared: RUNNING without ready must NOT
        # complete the step (reference: readiness label gating,
        # DeploymentStep.java:163-193)
        SendTaskRunning("world-0-server", ready=False),
        ExpectStepStatus(
            "deploy", "world", "world-0:[server]", Status.STARTED
        ),
        SendTaskRunning("world-0-server"),
        AdvanceCycles(1),
        ExpectLaunchedTasks("world-1-server"),
        SendTaskRunning("world-1-server"),
        ExpectDeploymentComplete(),
    ])
    info = runner.world.agent.task_info_of("hello-0-server")
    assert "hello-container-path" in info.command
    assert runner.world.agent.task_info_of("world-1-server") is not None


def test_simple_single_pod_deploy():
    """simple.yml: BASELINE config #1 — single-pod CPU-only deploy,
    plan PENDING -> COMPLETE."""
    runner = ServiceTestRunner(load("simple.yml"))
    runner.run([
        ExpectPlanStatus("deploy", Status.PENDING),
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-0-server"),
        SendTaskRunning("hello-0-server"),
        ExpectDeploymentComplete(),
    ])


def test_max_per_host_constraint():
    """max_per_host.yml: BASELINE config #2 — three instances, at most
    one per host; constraint respected and blocking until capacity."""
    hosts = [TpuHost(host_id=f"h{i}") for i in range(2)]
    runner = ServiceTestRunner(load("max_per_host.yml"), hosts=hosts)
    runner.run([
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-0-server"),
        SendTaskRunning("hello-0-server"),
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-1-server"),
        SendTaskRunning("hello-1-server"),
        # only 2 hosts: the third instance cannot place
        AdvanceCycles(2),
        ExpectNoLaunches(),
        ExpectDeclined("hello-[2]"),
        ExpectPlanStatus("deploy", Status.IN_PROGRESS),
        AddHost(TpuHost(host_id="h2")),
        ExpectLaunchedTasks("hello-2-server"),
        SendTaskRunning("hello-2-server"),
        ExpectDeploymentComplete(),
        ExpectDistinctHosts(
            "hello-0-server", "hello-1-server", "hello-2-server"
        ),
    ])


def test_canary_deploy_gated_on_proceed():
    """canary.yml: nothing launches until `plan continue`; after the
    canary count the remaining instances flow automatically."""
    runner = ServiceTestRunner(load("canary.yml"))
    runner.run([
        AdvanceCycles(2),
        ExpectNoLaunches(),
        ExpectPlanStatus("deploy", Status.WAITING),
        PlanContinue("deploy"),
        PlanContinue("deploy", "hello-deploy"),
        ExpectLaunchedTasks("hello-0-server"),
        SendTaskRunning("hello-0-server"),
        AdvanceCycles(1),
        ExpectNoLaunches(),
        PlanContinue("deploy", "hello-deploy"),
        ExpectLaunchedTasks("hello-1-server"),
        SendTaskRunning("hello-1-server"),
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-2-server"),
        SendTaskRunning("hello-2-server"),
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-3-server"),
        SendTaskRunning("hello-3-server"),
        ExpectDeploymentComplete(),
    ])


def test_multistep_plan_orders_init_before_server():
    """multistep_plan.yml: instance 0 runs its ONCE init task, then its
    server; instance 1 goes straight to server."""
    runner = ServiceTestRunner(load("multistep_plan.yml"))
    runner.run([
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-0-init"),
        SendTaskFinished("hello-0-init"),
        ExpectStepStatus("deploy", "hello-deploy", "hello-0:[init]",
                         Status.COMPLETE),
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-0-server"),
        SendTaskRunning("hello-0-server"),
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-1-server"),
        SendTaskRunning("hello-1-server"),
        ExpectDeploymentComplete(),
    ])
    # the ONCE init task ran exactly once, on instance 0 only
    assert len(runner.world.agent.launches_of("hello-0-init")) == 1
    assert runner.world.agent.task_id_of("hello-1-init") is None


def test_sidecar_plan_runs_on_start_and_reruns():
    """sidecar.yml: deploy completes without the sidecar task; `plan
    start` runs it; a second start re-runs it (backup-plan shape,
    reference: cassandra sidecar plans + PlansQueries.start)."""
    runner = ServiceTestRunner(load("sidecar.yml"))
    runner.run([
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-0-server"),
        SendTaskRunning("hello-0-server"),
        ExpectDeploymentComplete(),
        # sidecar plan exists, interrupted, not launched
        AdvanceCycles(2),
        ExpectNoLaunches(),
    ])
    assert runner.world.scheduler.plan("sidecar") is not None
    runner.run([
        PlanStart("sidecar"),
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-0-once"),
        SendTaskFinished("hello-0-once"),
        ExpectPlanStatus("sidecar", Status.COMPLETE),
    ])
    runner.run([
        PlanStart("sidecar"),
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-0-once"),
        SendTaskFinished("hello-0-once"),
        ExpectPlanStatus("sidecar", Status.COMPLETE),
    ])
    assert len(runner.world.agent.launches_of("hello-0-once")) == 2


def test_finish_state_goals_complete_and_stay_finished():
    """finish_state.yml: ONCE/FINISH tasks complete the deploy on
    TASK_FINISHED and are not relaunched afterwards; a scheduler
    restart does not re-run the ONCE task."""
    runner = ServiceTestRunner(load("finish_state.yml"))
    runner.run([
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-0-init"),
        SendTaskFinished("hello-0-init"),
        AdvanceCycles(1),
        ExpectLaunchedTasks("world-0-batch"),
        SendTaskFinished("world-0-batch"),
        AdvanceCycles(1),
        ExpectLaunchedTasks("world-1-batch"),
        SendTaskFinished("world-1-batch"),
        ExpectDeploymentComplete(),
        AdvanceCycles(2),
        ExpectNoLaunches(),
    ])
    restarted = runner.restart()
    restarted.run([
        AdvanceCycles(2),
        ExpectNoLaunches(),
        ExpectDeploymentComplete(),
    ])
    assert len(restarted.agent.launches_of("hello-0-init")) == 1


def test_pod_mount_volume_shared_between_tasks():
    """pod_mount_volume.yml: a pod-level MOUNT volume gives BOTH tasks
    of the pod one durable volume key (reference: pod-mount-volume.yml
    + resource-set volume sharing), and a plain restart keeps it while
    pod replace rotates it."""
    runner = ServiceTestRunner(load("pod_mount_volume.yml"))
    runner.run([
        AdvanceCycles(1),
        ExpectLaunchedTasks("data-0-writer"),
        SendTaskFinished("data-0-writer"),
        AdvanceCycles(1),
        ExpectLaunchedTasks("data-0-server"),
        SendTaskRunning("data-0-server"),
        ExpectDeploymentComplete(),
    ])
    ledger = runner.world.scheduler.ledger

    def volume_key(task: str) -> str:
        vols = {}
        for res in ledger.for_task(task):
            vols.update(res.volumes or {})
        assert "shared-data" in vols, f"no shared-data volume on {task}"
        return vols["shared-data"]

    writer_key = volume_key("data-0-writer")
    assert writer_key == volume_key("data-0-server")

    # restart (TRANSIENT relaunch) keeps the durable volume; replace
    # (PERMANENT) starts empty with a fresh key
    scheduler = runner.world.scheduler
    scheduler.restart_pod("data", 0)
    runner.run([
        AdvanceCycles(2),
        SendTaskRunning("data-0-server"),
        AdvanceCycles(1),
    ])
    assert volume_key("data-0-server") == writer_key
    scheduler.restart_pod("data", 0, replace=True)
    runner.run([
        AdvanceCycles(2),
        SendTaskRunning("data-0-server"),
        AdvanceCycles(1),
    ])
    assert volume_key("data-0-server") != writer_key


def test_pre_reserved_role_places_only_on_reserved_hosts():
    """pre_reserved.yml: a pod with pre-reserved-role only lands on
    hosts carved out for that role (reserved_role attribute); the
    second instance BLOCKS until a second reserved host exists
    (reference: pre-reserved-role + PreReservationCannotChange)."""
    hosts = [
        TpuHost(host_id="plain-0"),
        TpuHost(host_id="res-0", attributes={"reserved_role": "dedicated"}),
    ]
    runner = ServiceTestRunner(load("pre_reserved.yml"), hosts=hosts)
    runner.run([
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-0-server"),
        SendTaskRunning("hello-0-server"),
        AdvanceCycles(2),
        ExpectNoLaunches(),  # plain-0 is not reserved for the role
        ExpectPlanStatus("deploy", Status.IN_PROGRESS),
        AddHost(TpuHost(
            host_id="res-1", attributes={"reserved_role": "dedicated"},
        )),
        ExpectLaunchedTasks("hello-1-server"),
        SendTaskRunning("hello-1-server"),
        ExpectDeploymentComplete(),
    ])
    for name in ("hello-0-server", "hello-1-server"):
        info = runner.agent.task_info_of(name)
        assert info.agent_id.startswith("res-"), (
            f"{name} placed on unreserved host {info.agent_id}"
        )


def test_unreserved_pods_never_consume_reserved_hosts():
    """The carve-out holds in BOTH directions: an ordinary pod (no
    pre-reserved-role) must not land on a reserved host even when it
    is the only host with capacity (reference: pre-reserved resources
    are invisible to other roles)."""
    hosts = [TpuHost(
        host_id="res-0", attributes={"reserved_role": "dedicated"},
    )]
    runner = ServiceTestRunner(load("simple.yml"), hosts=hosts)
    runner.run([
        AdvanceCycles(3),
        ExpectNoLaunches(),
        ExpectPlanStatus("deploy", Status.PENDING),
        AddHost(TpuHost(host_id="plain-0")),
        ExpectLaunchedTasks("hello-0-server"),
        SendTaskRunning("hello-0-server"),
        ExpectDeploymentComplete(),
    ])
    assert runner.agent.task_info_of("hello-0-server").agent_id == "plain-0"


def test_zone_placement_max_per_zone():
    """zone.yml: max-per-zone:1 — two hosts in one zone cannot take
    two instances; deploy blocks until a distinct zone appears
    (reference: MaxPerZoneRule / ZoneValidator flows)."""
    hosts = [
        TpuHost(host_id="a0", zone="zone-a"),
        TpuHost(host_id="a1", zone="zone-a"),
        TpuHost(host_id="b0", zone="zone-b"),
    ]
    runner = ServiceTestRunner(load("zone.yml"), hosts=hosts)
    runner.run([
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-0-server"),
        SendTaskRunning("hello-0-server"),
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-1-server"),
        SendTaskRunning("hello-1-server"),
        AdvanceCycles(2),
        ExpectNoLaunches(),  # zone-a and zone-b are taken; a1 is blocked
        ExpectPlanStatus("deploy", Status.IN_PROGRESS),
        AddHost(TpuHost(host_id="c0", zone="zone-c")),
        ExpectLaunchedTasks("hello-2-server"),
        SendTaskRunning("hello-2-server"),
        ExpectDeploymentComplete(),
    ])
    zones = set()
    for i in range(3):
        info = runner.agent.task_info_of(f"hello-{i}-server")
        host = next(
            h for h in runner.world.inventory.hosts()
            if h.host_id == info.agent_id
        )
        zones.add(host.zone)
    assert len(zones) == 3


def test_once_goal_survives_restart_but_reruns_on_replace():
    """once_goal.yml: the ONCE init runs exactly once per pod
    incarnation — scheduler restart does not re-run it, pod REPLACE
    does (fresh incarnation re-runs init before the server)."""
    runner = ServiceTestRunner(load("once_goal.yml"))
    runner.run([
        AdvanceCycles(1),
        ExpectLaunchedTasks("node-0-init"),
        SendTaskFinished("node-0-init"),
        AdvanceCycles(1),
        ExpectLaunchedTasks("node-0-server"),
        SendTaskRunning("node-0-server"),
        ExpectDeploymentComplete(),
    ])
    restarted = runner.restart()
    restarted.run([
        AdvanceCycles(2),
        ExpectNoLaunches(),
        ExpectDeploymentComplete(),
    ])
    assert len(restarted.agent.launches_of("node-0-init")) == 1

    # pod replace: a fresh incarnation re-runs init alongside the
    # server (recovery relaunches the pod's tasks as one unit)
    restarted.world.scheduler.restart_pod("node", 0, replace=True)
    restarted.run([
        AdvanceCycles(2),
        ExpectLaunchedTasks("node-0-init", "node-0-server"),
        SendTaskFinished("node-0-init"),
        SendTaskRunning("node-0-server"),
        AdvanceCycles(1),
    ])
    assert len(restarted.agent.launches_of("node-0-init")) == 2


def test_overlay_network_membership_in_task_contract():
    """overlay.yml: network membership lands in the task's label + env
    contract, and joining a network later is a rejected update
    (network-regime validator)."""
    from dcos_commons_tpu.common import Label
    from dcos_commons_tpu.specification import (
        ConfigValidationError,
        from_yaml,
        validate_spec_change,
    )

    runner = ServiceTestRunner(load("overlay.yml"))
    runner.run([
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-0-server"),
        SendTaskRunning("hello-0-server"),
        ExpectDeploymentComplete(),
    ])
    info = runner.agent.task_info_of("hello-0-server")
    assert info.labels[Label.NETWORKS] == "dcos"
    assert info.env["TASK_NETWORKS"] == "dcos"
    # leaving the overlay on update: rejected
    on_net = from_yaml(load("overlay.yml"), {"FRAMEWORK_NAME": "s"})
    off_net = from_yaml(load("simple.yml"), {"FRAMEWORK_NAME": "s"})
    with pytest.raises(ConfigValidationError) as err:
        validate_spec_change(on_net, off_net)
    assert "networks" in str(err.value)


def test_profile_mount_volume_gates_placement():
    """profile_mount.yml: the ssd-profile volume places only on hosts
    advertising the profile; deploy blocks (with a traceable reason)
    until one exists."""
    hosts = [TpuHost(host_id="spinny-0")]  # no volume_profiles
    runner = ServiceTestRunner(load("profile_mount.yml"), hosts=hosts)
    runner.run([
        AdvanceCycles(3),
        ExpectNoLaunches(),
        ExpectPlanStatus("deploy", Status.PENDING),
        AddHost(TpuHost(
            host_id="fast-0",
            attributes={"volume_profiles": "ssd,nvme"},
        )),
        ExpectLaunchedTasks("data-0-server"),
        SendTaskRunning("data-0-server"),
        ExpectDeploymentComplete(),
    ])
    assert runner.agent.task_info_of("data-0-server").agent_id == "fast-0"
    # the refusal is explainable (reference: OfferOutcomeTracker)
    trace = runner.world.scheduler.outcome_tracker.to_json()
    assert "volume_profiles" in str(trace) or "profile" in str(trace)


def test_share_pid_namespace_label():
    """share_pid.yml: both tasks carry the shared-pid contract."""
    from dcos_commons_tpu.common import Label

    runner = ServiceTestRunner(load("share_pid.yml"))
    runner.run([
        AdvanceCycles(1),
        ExpectLaunchedTasks("duo-0-server", "duo-0-watchdog"),
        SendTaskRunning("duo-0-server"),
        SendTaskRunning("duo-0-watchdog"),
        ExpectDeploymentComplete(),
    ])
    for name in ("duo-0-server", "duo-0-watchdog"):
        info = runner.agent.task_info_of(name)
        assert info.labels[Label.SHARE_PID_NAMESPACE] == "true"


def test_crash_loop_delays_relaunch():
    """crash-loop.yml: with backoff enabled, repeated failures push the
    step to DELAYED instead of hot-looping relaunches (reference:
    ExponentialBackoff -> DELAYED, DeploymentStep.java:176-182)."""
    runner = ServiceTestRunner(
        load("crash-loop.yml"),
        scheduler_config=SchedulerConfig(
            backoff_enabled=True,
            backoff_initial_s=60.0,
            backoff_factor=2.0,
            backoff_max_s=300.0,
        ),
    )
    runner.run([
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-0-server"),
        SendTaskFailed("hello-0-server"),
        ExpectStepStatus("deploy", "hello", "hello-0:[server]",
                         Status.DELAYED),
        AdvanceCycles(2),
        ExpectNoLaunches(),
        ExpectPlanStatus("deploy", Status.DELAYED),
    ])
    assert len(runner.world.agent.launches_of("hello-0-server")) == 1


def test_custom_update_plan_used_after_deploy():
    """update_plan.yml: initial rollout uses the serial deploy plan; a
    config change afterwards rolls through the custom parallel update
    plan (reference: SchedulerBuilder.selectDeployPlan:644)."""
    yaml_text = load("update_plan.yml")
    runner = ServiceTestRunner(yaml_text)
    runner.run([
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-0-server"),
        SendTaskRunning("hello-0-server"),
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-1-server"),
        SendTaskRunning("hello-1-server"),
        ExpectDeploymentComplete(),
    ])
    updated = ServiceTestRunner(
        yaml_text,
        persister=runner.persister,
        hosts=runner.hosts,
        env={"SLEEP_DURATION": "2000"},
    )
    updated.agent = runner.agent
    updated.inventory = runner.inventory
    kill_mark = len(runner.agent.kills)
    world = updated.run([
        AdvanceCycles(1),
        # parallel update strategy: both instances roll in one cycle
        ExpectTaskKilled("hello-0-server"),
        SendTaskRunning("hello-0-server"),
        SendTaskRunning("hello-1-server"),
        ExpectPlanStatus("update", Status.COMPLETE),
    ])
    from dcos_commons_tpu.common import task_name_of

    rolled = {task_name_of(k) for k in world.agent.kills[kill_mark:]}
    assert rolled == {"hello-0-server", "hello-1-server"}
    assert "sleep 2000" in world.agent.task_info_of("hello-0-server").command


def test_decommission_on_world_scale_down():
    """svc.yml with WORLD_COUNT dropped 2 -> 1: world-1 is killed and
    its reservations erased through the decommission plan
    (allow-decommission: true on the world pod)."""
    runner = ServiceTestRunner(load("svc.yml"))
    runner.run([
        AdvanceCycles(1),
        SendTaskRunning("hello-0-server"),
        AdvanceCycles(1),
        SendTaskRunning("world-0-server"),
        AdvanceCycles(1),
        SendTaskRunning("world-1-server"),
        ExpectDeploymentComplete(),
    ])
    scaled = ServiceTestRunner(
        load("svc.yml"),
        persister=runner.persister,
        hosts=runner.hosts,
        env={"WORLD_COUNT": "1"},
    )
    scaled.agent = runner.agent
    scaled.inventory = runner.inventory
    world = scaled.run([
        AdvanceCycles(1),
        ExpectTaskKilled("world-1-server"),
    ])
    plan = world.scheduler.plan("decommission")
    assert plan is not None
    # confirm the kill, then let the erase steps run to completion
    scaled.run([
        SendTaskFailed("world-1-server"),
        AdvanceCycles(3),
        ExpectPlanStatus("decommission", Status.COMPLETE),
    ])
    assert world.state_store.fetch_task("world-1-server") is None


def test_taskcfg_env_routed_into_launched_tasks():
    """taskcfg.yml + TASKCFG_* scheduler env: routed vars appear in the
    launched TaskInfo env (reference: TaskEnvRouter.java:17-30)."""
    from dcos_commons_tpu.testing import ExpectTaskEnv

    runner = ServiceTestRunner(
        load("taskcfg.yml"),
        env={
            "TASKCFG_ALL_GREETING": "howdy-all",
            "TASKCFG_HELLO_GREETING": "howdy-hello",
        },
    )
    runner.run([
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-0-server"),
        ExpectTaskEnv("hello-0-server", "GREETING", "howdy-hello"),
        SendTaskRunning("hello-0-server"),
        ExpectDeploymentComplete(),
    ])


def test_taskcfg_template_rendered_and_rerendered_on_update(tmp_path):
    """The per-task config plane end to end: the agent daemon renders
    server.properties into the sandbox from the task env, and a config
    update (new TASKCFG value -> new target config) relaunches the task
    with a re-rendered file (reference: sdk/bootstrap/main.go:291-376
    render; config update rolling relaunch)."""
    import time as _time

    from dcos_commons_tpu.agent.daemon import AgentDaemon
    from dcos_commons_tpu.agent.remote import RemoteFleet
    from dcos_commons_tpu.offer.inventory import SliceInventory, TpuHost
    from dcos_commons_tpu.scheduler import SchedulerBuilder, SchedulerConfig
    from dcos_commons_tpu.specification import from_yaml_file
    from dcos_commons_tpu.storage import MemPersister

    daemon = AgentDaemon("h0", str(tmp_path / "sandbox-h0")).start()
    try:
        fleet = RemoteFleet()
        fleet.add_host("h0", daemon.url)
        persister = MemPersister()
        hosts = [TpuHost(host_id="h0")]

        def build(greeting):
            spec = from_yaml_file(
                os.path.join(HELLOWORLD, "taskcfg.yml"),
                env={"TASKCFG_ALL_GREETING": greeting},
            )
            builder = SchedulerBuilder(
                spec,
                SchedulerConfig(
                    sandbox_root=str(tmp_path / "unused"),
                    backoff_enabled=False,
                ),
                persister,
            )
            builder.set_inventory(SliceInventory(hosts))
            builder.set_agent(fleet)
            return builder.build()

        def drive(scheduler, until, timeout_s=20.0):
            deadline = _time.monotonic() + timeout_s
            while _time.monotonic() < deadline:
                scheduler.run_cycle()
                if until(scheduler):
                    return True
                _time.sleep(0.05)
            return False

        scheduler = build("v1")
        assert drive(
            scheduler, lambda s: s.deploy_manager.get_plan().is_complete
        )
        rendered = fleet.client("h0").sandbox_file(
            "hello-0-server", "server.properties"
        )
        assert "greeting=v1" in rendered
        assert "pod-index=0" in rendered
        assert "hostname=hello-0-server" in rendered

        # config update: new TASKCFG value -> new target -> re-render
        updated = build("v2")
        assert drive(
            updated, lambda s: s.deploy_manager.get_plan().is_complete
        )
        rendered = fleet.client("h0").sandbox_file(
            "hello-0-server", "server.properties"
        )
        assert "greeting=v2" in rendered
    finally:
        daemon.stop()


def test_nonessential_yaml_scoped_recovery():
    """nonessential_tasks.yml: sidecar death recovers alone; essential
    death takes the pod (TaskSpec.isEssential semantics)."""
    runner = ServiceTestRunner(load("nonessential_tasks.yml"))
    runner.run([
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-0-essential", "hello-0-nonessential"),
        SendTaskRunning("hello-0-essential"),
        SendTaskRunning("hello-0-nonessential"),
        ExpectDeploymentComplete(),
        SendTaskFailed("hello-0-nonessential"),
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-0-nonessential"),
        SendTaskRunning("hello-0-nonessential"),
    ])
    assert len(runner.world.agent.launches_of("hello-0-essential")) == 1
    runner.run([
        SendTaskFailed("hello-0-essential"),
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-0-essential", "hello-0-nonessential"),
    ])


def test_multiport_env_and_endpoint_discovery():
    """multiport.yml: fixed + dynamic + VIP ports land in task env
    under their keys, stay distinct per host, and surface through
    /v1/endpoints for clients (reference: EndpointUtils/VIPs)."""
    from dcos_commons_tpu.http import ApiServer

    runner = ServiceTestRunner(load("multiport.yml"))
    runner.run([
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-0-server"),
        SendTaskRunning("hello-0-server"),
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-1-server"),
        SendTaskRunning("hello-1-server"),
        ExpectDeploymentComplete(),
    ])
    agent = runner.world.agent
    for i in range(2):
        env = agent.task_info_of(f"hello-{i}-server").env
        assert env["PORT_HTTP"] == "8080"
        admin, gossip = int(env["PORT_ADMIN"]), int(env["PORT_GOSSIP"])
        assert admin > 0 and gossip > 0 and admin != gossip
    server = ApiServer(runner.world.scheduler).start()
    try:
        import json
        import urllib.request

        def get(p):
            with urllib.request.urlopen(server.url + p, timeout=5) as r:
                return json.loads(r.read())

        names = get("/v1/endpoints")
        assert "http" in names and "admin" in names
        http_ep = get("/v1/endpoints/http")
        assert len(http_ep["address"]) == 2  # one per instance
        # the VIP name resolves to the same backend set
        assert "vip:web" in names
        vip_ep = get("/v1/endpoints/vip:web")
        assert sorted(vip_ep["address"]) == sorted(http_ep["address"])
    finally:
        server.stop()


def test_graceful_shutdown_honors_kill_grace(tmp_path):
    """graceful-shutdown.yml through a REAL agent: pod restart sends
    SIGTERM, the task's trap takes ~1s of cleanup INSIDE the kill-grace
    window (so an immediate-SIGKILL regression cannot pass), and the
    supervisor's durable record shows a graceful exit 0."""
    from dcos_commons_tpu.agent.local import LocalProcessAgent
    from dcos_commons_tpu.offer.inventory import SliceInventory, TpuHost
    from dcos_commons_tpu.scheduler import SchedulerBuilder, SchedulerConfig
    from dcos_commons_tpu.specification import from_yaml
    from dcos_commons_tpu.storage import MemPersister
    from dcos_commons_tpu.testing import drive_until

    spec = from_yaml(load("graceful-shutdown.yml"))
    builder = SchedulerBuilder(
        spec,
        SchedulerConfig(
            sandbox_root=str(tmp_path / "sbx"),
            backoff_enabled=False,
            revive_capacity=1_000_000,
        ),
        MemPersister(),
    )
    builder.set_inventory(SliceInventory([TpuHost(host_id="h0")]))
    agent = LocalProcessAgent(str(tmp_path / "sbx"))
    builder.set_agent(agent)
    scheduler = builder.build()
    try:
        assert drive_until(
            scheduler,
            lambda: scheduler.deploy_manager.get_plan().is_complete,
        )
        first_id = scheduler.state_store.fetch_task(
            "world-0-server"
        ).task_id
        # operator restart: SIGTERM -> trap (sleeps 1s) -> exit 0
        scheduler.restart_pod("world", 0)
        assert drive_until(
            scheduler,
            lambda: (
                (info := scheduler.state_store.fetch_task(
                    "world-0-server"
                )) is not None and info.task_id != first_id
            ),
        )
        # the trap SLEEPS 1s before writing: an immediate-SIGKILL
        # regression would cut it mid-sleep and the line could never
        # appear (the old incarnation's .super record is pruned at
        # relaunch, so the log is the durable proof)
        cleanup = tmp_path / "sbx" / "world-0-server" / "shutdown.log"
        assert cleanup.read_text().strip() == "cleaned-up"
    finally:
        agent.shutdown()


def test_rlimits_enforced_in_real_task(tmp_path):
    """rlimits.yml through a REAL agent: the task's own `ulimit`
    reports the spec's soft/hard NOFILE limits and a zero core limit —
    enforcement at exec time, not just spec plumbing (reference:
    svc.yml:9-13 rlimits -> RLimitSpec.java -> containerizer
    RLimitInfo)."""
    from dcos_commons_tpu.agent.local import LocalProcessAgent
    from dcos_commons_tpu.offer.inventory import SliceInventory
    from dcos_commons_tpu.scheduler import SchedulerBuilder
    from dcos_commons_tpu.specification import from_yaml
    from dcos_commons_tpu.storage import MemPersister
    from dcos_commons_tpu.testing import drive_until

    spec = from_yaml(load("rlimits.yml"))
    assert spec.pod("hello").rlimits[0].name == "RLIMIT_NOFILE"
    builder = SchedulerBuilder(
        spec,
        SchedulerConfig(
            sandbox_root=str(tmp_path / "sbx"),
            backoff_enabled=False,
            revive_capacity=1_000_000,
        ),
        MemPersister(),
    )
    builder.set_inventory(SliceInventory([TpuHost(host_id="h0")]))
    agent = LocalProcessAgent(str(tmp_path / "sbx"))
    builder.set_agent(agent)
    scheduler = builder.build()
    try:
        assert drive_until(
            scheduler,
            lambda: scheduler.deploy_manager.get_plan().is_complete,
        )
        sandbox = tmp_path / "sbx" / "hello-0-server"
        assert (sandbox / "nofile_soft").read_text().strip() == "64"
        assert (sandbox / "nofile_hard").read_text().strip() == "128"
        assert (sandbox / "core_soft").read_text().strip() == "0"
    finally:
        agent.shutdown()


def test_custom_steps_serial_strategy_serial_steps():
    """custom_steps.yml: operator-chosen step groupings — serial
    strategy with serial per-task steps deploys first -> second ->
    third per instance, instance by instance (reference:
    CustomStepsTest.testSerialStrategySerialSteps)."""
    runner = ServiceTestRunner(
        load("custom_steps.yml"),
        env={
            "HELLO_COUNT": "2",
            "DEPLOY_STRATEGY": "serial",
            "DEPLOY_STEPS": '[["first"], ["second"]]',
        },
    )
    runner.run([
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-0-first"),
        AdvanceCycles(2),
        ExpectNoLaunches(),  # second waits for first to RUN
        SendTaskRunning("hello-0-first"),
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-0-second"),
        SendTaskRunning("hello-0-second"),
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-1-first"),
        SendTaskRunning("hello-1-first"),
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-1-second"),
        SendTaskRunning("hello-1-second"),
        ExpectDeploymentComplete(),
    ])
    # 'third' was not in the chosen steps: never launched
    assert runner.world.agent.task_id_of("hello-0-third") is None


def test_custom_steps_parallel_strategy_mixed_steps():
    """custom_steps.yml: parallel strategy with a MIXED grouping —
    [first, second] launch together, third gates on them (reference:
    CustomStepsTest parallel/mixed permutations)."""
    runner = ServiceTestRunner(
        load("custom_steps.yml"),
        env={
            "HELLO_COUNT": "1",
            "DEPLOY_STRATEGY": "parallel",
            "DEPLOY_STEPS": '[["first", "second"], ["third"]]',
        },
    )
    runner.run([
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-0-first", "hello-0-second"),
        SendTaskRunning("hello-0-first"),
        AdvanceCycles(2),
        ExpectNoLaunches(),  # third needs BOTH peers running
        SendTaskRunning("hello-0-second"),
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-0-third"),
        SendTaskRunning("hello-0-third"),
        ExpectDeploymentComplete(),
    ])


def test_executor_volume_shared_across_resource_set():
    """executor_volume.yml: pod-level volumes (both the single
    `volume:` and the `volumes:` map dialects) give every task of the
    pod — servers and ONCE sidecars alike — ONE durable volume key;
    the sidecar plan reuses it (reference: executor_volume.yml)."""
    runner = ServiceTestRunner(
        load("executor_volume.yml"),
        env={"HELLO_COUNT": "1", "WORLD_COUNT": "1"},
    )
    runner.run([
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-0-server"),
        SendTaskRunning("hello-0-server"),
        AdvanceCycles(1),
        ExpectLaunchedTasks("world-0-server"),
        SendTaskRunning("world-0-server"),
        AdvanceCycles(1),
        ExpectLaunchedTasks("world-0-once"),
        SendTaskFinished("world-0-once"),
        ExpectDeploymentComplete(),
    ])
    ledger = runner.world.scheduler.ledger

    def volume_key(task: str, path: str) -> str:
        vols = {}
        for res in ledger.for_task(task):
            vols.update(res.volumes or {})
        assert path in vols, f"no {path} volume on {task}"
        return vols[path]

    # world's server and ONCE task share the pod volume
    assert volume_key("world-0-server", "world-container-path") == \
        volume_key("world-0-once", "world-container-path")
    # the operator-run sidecar plan launches the hello sidecar on the
    # SAME pod volume as the running server
    runner.run([
        PlanStart("sidecar"),
        AdvanceCycles(2),
        ExpectLaunchedTasks("hello-0-sidecar"),
        SendTaskFinished("hello-0-sidecar"),
        ExpectPlanStatus("sidecar", Status.COMPLETE),
    ])
    assert volume_key("hello-0-server", "hello-container-path") == \
        volume_key("hello-0-sidecar", "hello-container-path")


def test_pre_reserved_sidecar_carveout_and_rerun():
    """pre-reserved-sidecar.yml: the role carve-out and the sidecar
    plan COMPOSE — the pod (server + ONCE sidecar on a shared pod
    volume) lands only on reserved hosts, and the sidecar re-runs via
    the sidecar plan on the same reservation (reference:
    pre-reserved-sidecar.yml)."""
    hosts = [
        TpuHost(host_id="plain-0"),
        TpuHost(host_id="res-0", attributes={"reserved_role": "dedicated"}),
    ]
    runner = ServiceTestRunner(
        load("pre-reserved-sidecar.yml"), hosts=hosts,
        env={"HELLO_COUNT": "1"},
    )
    runner.run([
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-0-server"),
        SendTaskRunning("hello-0-server"),
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-0-sidecar"),
        SendTaskFinished("hello-0-sidecar"),
        ExpectDeploymentComplete(),
    ])
    for name in ("hello-0-server", "hello-0-sidecar"):
        info = runner.agent.task_info_of(name)
        assert info.agent_id == "res-0", (
            f"{name} placed on unreserved host {info.agent_id}"
        )
    ledger = runner.world.scheduler.ledger

    def volume_key(task: str) -> str:
        vols = {}
        for res in ledger.for_task(task):
            vols.update(res.volumes or {})
        return vols.get("pod-container-path")

    assert volume_key("hello-0-server") == volume_key("hello-0-sidecar")
    first_sidecar_id = runner.agent.task_id_of("hello-0-sidecar")
    runner.run([
        PlanStart("sidecar"),
        AdvanceCycles(2),
        ExpectLaunchedTasks("hello-0-sidecar"),
        SendTaskFinished("hello-0-sidecar"),
        ExpectPlanStatus("sidecar", Status.COMPLETE),
    ])
    assert runner.agent.task_id_of("hello-0-sidecar") != first_sidecar_id


def test_foobar_service_name_naming_flows():
    """foobar_service_name.yml: a service name unrelated to pod/task
    names — ids, endpoints, and TASKCFG routing key off the YAML's own
    names (reference: foobar_service_name.yml)."""
    runner = ServiceTestRunner(
        load("foobar_service_name.yml"),
        env={"HELLO_COUNT": "1", "TASKCFG_ALL_EXTRA_FLAG": "on"},
    )
    runner.run([
        AdvanceCycles(1),
        ExpectLaunchedTasks("foo-0-bar"),
        SendTaskRunning("foo-0-bar"),
        ExpectDeploymentComplete(),
    ])
    assert runner.world.scheduler.spec.name == "foobar"
    info = runner.agent.task_info_of("foo-0-bar")
    assert info.env.get("EXTRA_FLAG") == "on"  # TASKCFG_ALL_* routed


def test_marathon_constraint_yaml_end_to_end():
    """marathon_constraint.yml: operator-supplied Marathon-JSON
    placement — hello UNIQUE spreads across hosts, world CLUSTER pins
    to one named host (reference: marathon_constraint.yml through the
    PlacementUtils-style JSON parser)."""
    hosts = [TpuHost(host_id=f"h{i}") for i in range(3)]
    runner = ServiceTestRunner(
        load("marathon_constraint.yml"), hosts=hosts,
        env={
            "HELLO_COUNT": "2",
            "WORLD_COUNT": "2",
            "WORLD_PLACEMENT": '[["hostname", "CLUSTER", "h2"]]',
        },
    )
    runner.run([
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-0-server"),
        SendTaskRunning("hello-0-server"),
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-1-server"),
        SendTaskRunning("hello-1-server"),
        AdvanceCycles(1),
        ExpectLaunchedTasks("world-0-server"),
        SendTaskRunning("world-0-server"),
        AdvanceCycles(1),
        ExpectLaunchedTasks("world-1-server"),
        SendTaskRunning("world-1-server"),
        ExpectDeploymentComplete(),
        ExpectDistinctHosts("hello-0-server", "hello-1-server"),
    ])
    for name in ("world-0-server", "world-1-server"):
        assert runner.agent.task_info_of(name).agent_id == "h2"


def test_pause_yaml_task_level_pause_resume():
    """pause.yml: pause ONE health-checked task of a two-task pod —
    the paused task relaunches on the idle command with checks
    suspended; its essential companion rides the pod relaunch but
    keeps its REAL command (reference semantics: an essential task's
    recovery relaunches every launched task of the pod,
    TaskUtils.java:454-462); resume restores the real command."""
    from dcos_commons_tpu.state import GoalStateOverride

    runner = ServiceTestRunner(load("pause.yml"), env={"HELLO_COUNT": "1"})
    runner.run([
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-0-server"),
        SendTaskRunning("hello-0-server"),
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-0-companion"),
        SendTaskRunning("hello-0-companion"),
        ExpectDeploymentComplete(),
    ])
    scheduler = runner.world.scheduler
    touched = scheduler.pause_pod("hello", 0, tasks=["server"])
    assert touched == ["hello-0-server"]
    runner.run([
        AdvanceCycles(3),
        SendTaskRunning("hello-0-server"),
        SendTaskRunning("hello-0-companion"),
        AdvanceCycles(1),
    ])
    # the paused relaunch idles; the companion (relaunched with the
    # pod, reference essential semantics) keeps its real command
    info = runner.agent.task_info_of("hello-0-server")
    assert "sleep" in info.command and "output" not in info.command
    assert scheduler.state_store.fetch_goal_override(
        "hello-0-server"
    )[0] is GoalStateOverride.PAUSED
    assert "output" in runner.agent.task_info_of(
        "hello-0-companion"
    ).command
    assert scheduler.state_store.fetch_goal_override(
        "hello-0-companion"
    )[0] is GoalStateOverride.NONE
    checks = runner.agent.checks.get(
        runner.agent.task_id_of("hello-0-server")
    )
    assert checks["health"] is None, "paused task kept its health check"
    scheduler.resume_pod("hello", 0, tasks=["server"])
    runner.run([
        AdvanceCycles(3),
        SendTaskRunning("hello-0-server"),
        SendTaskRunning("hello-0-companion"),
        AdvanceCycles(1),
    ])
    info = runner.agent.task_info_of("hello-0-server")
    assert "output" in info.command  # real command restored
    assert scheduler.state_store.fetch_goal_override(
        "hello-0-server"
    )[0] is GoalStateOverride.NONE
