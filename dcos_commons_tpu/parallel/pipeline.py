"""Pipeline parallelism: layer stages over the ``pp`` mesh axis.

GPipe-style microbatch schedule expressed the TPU way: every device
holds one contiguous stage of layers; activations move to the next
stage with a single ``lax.ppermute`` per tick, so each hop is one ICI
transfer and the whole schedule is a statically-bounded ``fori_loop``
that XLA can pipeline (no data-dependent control flow).

The schedule runs M microbatches through S stages in M + S - 1 ticks.
Each device computes its stage every tick; warm-up/drain bubbles are
the standard GPipe bubble (S-1)/(M+S-1).  Differentiable end to end —
reverse-mode AD through ppermute gives the reverse-direction gradient
permutes automatically, which is exactly the backward pipeline.

Runs inside shard_map with the ``pp`` axis bound.  Stage params are
whatever pytree the caller's ``stage_fn`` consumes — shard their
leading (layer) axis over ``pp`` so each device holds only its own
layers (see ``stage_params_spec``).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax

from dcos_commons_tpu.parallel.compat import axis_size


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    microbatches: jax.Array,
    axis_name: str = "pp",
) -> jax.Array:
    """Run microbatches through the pipeline.

    Args:
        stage_fn: (stage_params, x) -> y, this device's stage (its
            slice of the layer stack).
        stage_params: this device's shard of the params.
        microbatches: [M, microbatch, ...] — the full input, identical
            on every pp rank (replicated); only rank 0 actually feeds
            it into the pipe.
        axis_name: the pipeline mesh axis.

    Returns:
        [M, microbatch, ...] outputs — valid on the LAST pp rank
        (other ranks hold zeros).  Use :func:`last_stage_value` to
        broadcast to all ranks when the loss is computed replicated.
    """
    n_stages = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    n_micro = microbatches.shape[0]
    ticks = n_micro + n_stages - 1
    # send stage s -> s+1; the wrap edge (last -> 0) carries garbage
    # that rank 0 always overwrites with a fresh microbatch
    perm = [(s, (s + 1) % n_stages) for s in range(n_stages)]

    def vary(x):
        from dcos_commons_tpu.parallel.compat import pvary

        return pvary(x, (axis_name,))

    state = vary(jnp.zeros_like(microbatches[0]))
    out = vary(jnp.zeros_like(microbatches))

    def tick(t, carry):
        state, out = carry
        feed = microbatches[jnp.minimum(t, n_micro - 1)]
        x = jnp.where(idx == 0, feed, state)
        y = stage_fn(stage_params, x)
        done_idx = t - (n_stages - 1)  # microbatch finishing this tick
        is_last = idx == n_stages - 1
        write = jnp.logical_and(is_last, done_idx >= 0)
        slot = jnp.maximum(done_idx, 0)
        out = jnp.where(
            write, out.at[slot].set(y), out
        )
        state = lax.ppermute(y, axis_name, perm)
        return state, out

    _, out = lax.fori_loop(0, ticks, tick, (state, out), unroll=False)
    return out


def last_stage_value(x: jax.Array, axis_name: str = "pp") -> jax.Array:
    """Broadcast the last pp rank's value to every rank (psum of a
    one-hot mask — one collective, keeps the loss replicated)."""
    idx = lax.axis_index(axis_name)
    n = axis_size(axis_name)
    mask = (idx == n - 1).astype(x.dtype)
    return lax.psum(x * mask, axis_name)


def split_microbatches(batch: jax.Array, n_micro: int) -> jax.Array:
    """[B, ...] -> [M, B/M, ...]; B must divide evenly (static shapes)."""
    b = batch.shape[0]
    if b % n_micro:
        raise ValueError(f"batch {b} not divisible into {n_micro} microbatches")
    return batch.reshape((n_micro, b // n_micro) + batch.shape[1:])


def merge_microbatches(micro: jax.Array) -> jax.Array:
    """Inverse of :func:`split_microbatches`."""
    return micro.reshape((micro.shape[0] * micro.shape[1],) + micro.shape[2:])
