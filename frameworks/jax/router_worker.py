"""Serving front door task: the multi-pod request router (ISSUE 12).

Deployed like any other task (svc_router.yml): discovers the serve
pods through the scheduler's ``GET /v1/endpoints/<vip>`` (generation-
stamped — a quiet fleet costs one compare per poll), polls each pod's
``GET /stats`` for the load gauges, and serves ``POST /generate`` on
the scheduler-assigned port with least-loaded + prefix-affinity +
drain-aware placement (dcos_commons_tpu/router/).

The router's own gauges mirror to ``servestats.json`` in the sandbox,
so the scheduler's /v1/debug/serving, /v1/debug/router, and the
ServingSloWatcher (SERVE_TTFT_SLO_S / SERVE_QUEUE_DEPTH_SLO on this
task's env) all see the front door through the plumbing serve pods
already use.  Readiness gates on the first discovery round having
run: the deploy plan completes only when the router can place a
request.

Entirely jax-free: the router is host-side scheduling, and must
deploy onto a CPU-only node in front of the TPU serve fleet.
"""

import os
import sys

sys.path.insert(0, os.environ.get("REPO_ROOT", "/root/repo"))

from dcos_commons_tpu.router.frontdoor import (  # noqa: E402
    RouterServer,
    default_stats_path,
)
from dcos_commons_tpu.security.auth import load_token  # noqa: E402


def main() -> int:
    scheduler_url = os.environ.get(
        "SCHEDULER_API_URL", "http://127.0.0.1:8080"
    )
    endpoint = os.environ.get("ROUTER_ENDPOINT", "vip:inference")
    port = int(os.environ.get("PORT_HTTP", "0"))
    # the affinity hash must mirror the pods' paging intern geometry:
    # both sides render the same KV_PAGE_TOKENS option
    page_tokens = int(os.environ.get("KV_PAGE_TOKENS") or "16")
    server = RouterServer(
        scheduler_url,
        endpoint=endpoint,
        port=port,
        poll_interval_s=float(
            os.environ.get("ROUTER_POLL_INTERVAL_S", "1.0")
        ),
        stats_path=default_stats_path(),
        auth_token=load_token(),
        # STRICTLY above the pods' queue timeout: a saturated pod
        # answers its 503 at SERVE_QUEUE_TIMEOUT_S, and the router's
        # socket timer must lose that race — a timeout here reads as
        # pod DEATH (failover + affinity eviction), and saturation
        # must never be misclassified as death exactly when the
        # fleet is loaded
        request_timeout_s=float(
            os.environ.get("SERVE_QUEUE_TIMEOUT_S", "600")
        ) + 30.0,
        page_tokens=max(1, page_tokens),
        policy=os.environ.get("ROUTER_POLICY", "affinity"),
        stale_after_s=float(
            os.environ.get("ROUTER_STALE_AFTER_S", "10")
        ),
        retry_budget=int(os.environ.get("ROUTER_RETRY_BUDGET", "2")),
        log=lambda msg: print(msg, flush=True),
    )
    # a RELAUNCH reuses the sandbox: drop the stale readiness marker
    try:
        os.remove("ready")
    except OSError:
        pass
    # readiness gates on the FIRST discovery round: the deploy plan
    # completes only when the router has a pod set to place into
    server.refresh_once()
    with open("ready", "w") as f:
        f.write("routing\n")
    print(
        f"router: fronting {endpoint} via {scheduler_url} on port "
        f"{server.port} (policy "
        f"{os.environ.get('ROUTER_POLICY', 'affinity')}, "
        f"{len(server.router.pods())} pod(s) discovered)",
        flush=True,
    )
    server.serve_forever()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
