"""Mixture-of-Experts FFN with expert parallelism over the ``ep`` axis.

TPU-first design, not a port: routing and dispatch are expressed as
one-hot einsums (dense matmuls the MXU eats) with a STATIC per-expert
capacity — no gather/scatter, no dynamic shapes, nothing XLA can't
tile.  Expert parallelism is two ``lax.all_to_all``s around the expert
FFN: dispatch local tokens to the ranks owning their experts, compute,
and send results back — the standard TPU MoE recipe (tokens ride ICI
both ways while the expert matmuls run).

Shapes (per device, inside shard_map over ``ep``):
    x            [tokens, d_model]      tokens sharded over ep
    dispatch     [tokens, E, C]         one-hot token->slot
    expert_in    [E, C, d]  --all_to_all-->  [E/ep, ep*C, d]
    expert_out   [E/ep, ep*C, d] --all_to_all--> [E, C, d]

Top-k routing with probability renormalisation over the chosen k, and
the switch-transformer load-balancing auxiliary loss.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from dcos_commons_tpu.parallel.compat import axis_size

from dcos_commons_tpu.models.quantize import dequantize_weight as dq


@dataclass(frozen=True)
class MoEConfig:
    d_model: int = 512
    d_ff: int = 1024            # per-expert SwiGLU hidden
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.5
    dtype: Any = jnp.bfloat16

    def capacity(self, n_tokens: int) -> int:
        """Static per-expert slot count for an n_tokens batch."""
        cap = int(self.capacity_factor * self.top_k * n_tokens / self.n_experts)
        return max(cap, 1)


MoEParams = Dict[str, jax.Array]


def init_moe_params(config: MoEConfig, key: jax.Array) -> MoEParams:
    keys = jax.random.split(key, 4)
    d, f, e = config.d_model, config.d_ff, config.n_experts
    dt = config.dtype

    def normal(key, shape, scale):
        return (jax.random.normal(key, shape, jnp.float32) * scale).astype(dt)

    return {
        # router stays f32: routing decisions are precision-sensitive
        "router": jax.random.normal(keys[0], (d, e), jnp.float32) * d ** -0.5,
        "w_gate": normal(keys[1], (e, d, f), d ** -0.5),
        "w_up": normal(keys[2], (e, d, f), d ** -0.5),
        "w_down": normal(keys[3], (e, f, d), f ** -0.5),
    }


def _routing(
    config: MoEConfig, params: MoEParams, x: jax.Array, capacity: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Token->expert-slot assignment as dense one-hot tensors.

    Returns (dispatch [t,E,C], combine [t,E,C], aux_loss scalar).
    """
    t = x.shape[0]
    e, k = config.n_experts, config.top_k
    logits = x.astype(jnp.float32) @ params["router"]
    probs = jax.nn.softmax(logits, axis=-1)                    # [t, E]
    gate_vals, expert_idx = lax.top_k(probs, k)                # [t, k]
    gate_vals = gate_vals / jnp.maximum(
        gate_vals.sum(-1, keepdims=True), 1e-9
    )
    # switch load-balance loss: fraction-of-tokens * mean-prob per expert
    top1_hot = jax.nn.one_hot(expert_idx[:, 0], e, dtype=jnp.float32)
    aux = e * jnp.mean(top1_hot.mean(0) * probs.mean(0))

    # slot assignment: k choices claim capacity in priority order, so
    # a token's 2nd choice never evicts another token's 1st choice
    dispatch = jnp.zeros((t, e, capacity), jnp.float32)
    combine = jnp.zeros((t, e, capacity), jnp.float32)
    used = jnp.zeros((e,), jnp.float32)                        # slots taken
    for slot_k in range(k):
        hot = jax.nn.one_hot(expert_idx[:, slot_k], e, dtype=jnp.float32)  # [t,E]
        pos = jnp.cumsum(hot, axis=0) - 1.0 + used[None, :]    # [t,E]
        keep = hot * (pos < capacity)
        slot_hot = keep[:, :, None] * jax.nn.one_hot(
            jnp.clip(pos, 0, capacity - 1).astype(jnp.int32),
            capacity, dtype=jnp.float32,
        )                                                       # [t,E,C]
        dispatch = dispatch + slot_hot
        combine = combine + slot_hot * gate_vals[:, slot_k][:, None, None]
        used = used + keep.sum(axis=0)
    return dispatch, combine, aux


def _routing_sorted(
    config: MoEConfig, params: MoEParams, x: jax.Array, capacity: int
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array, jax.Array]:
    """Sort-based token->slot assignment: no [t,E,C] one-hot tensors.

    Returns (slot [t*k], token [t*k], weight [t*k], keep [t*k], aux).
    The (t*k) routing entries are sorted by expert CHOICE-MAJOR (every
    token's 1st choice outranks any 2nd choice), positions within an
    expert come from the sorted order, and entries past the capacity
    are dropped — identical drop semantics to the one-hot path.  The
    per-entry work is O(t*k log(t*k)) sort + O(t*k) bookkeeping vs the
    one-hot path's O(t*E*C) tensor construction; dispatch becomes a
    row gather/scatter instead of a [t,E*C] matmul."""
    t = x.shape[0]
    e, k = config.n_experts, config.top_k
    logits = x.astype(jnp.float32) @ params["router"]
    probs = jax.nn.softmax(logits, axis=-1)                    # [t, E]
    gate_vals, expert_idx = lax.top_k(probs, k)                # [t, k]
    gate_vals = gate_vals / jnp.maximum(
        gate_vals.sum(-1, keepdims=True), 1e-9
    )
    top1_hot = jax.nn.one_hot(expert_idx[:, 0], e, dtype=jnp.float32)
    aux = e * jnp.mean(top1_hot.mean(0) * probs.mean(0))
    # choice-major flatten: stable argsort then gives 1st choices
    # priority over 2nd choices for the last slots of a hot expert
    flat_expert = expert_idx.T.reshape(-1)                     # [k*t]
    flat_token = jnp.tile(jnp.arange(t, dtype=jnp.int32), k)
    flat_gate = gate_vals.T.reshape(-1)
    order = jnp.argsort(flat_expert, stable=True)
    se = flat_expert[order]
    st = flat_token[order]
    sg = flat_gate[order]
    counts = jnp.bincount(flat_expert, length=e)
    offsets = jnp.concatenate(
        [jnp.zeros((1,), counts.dtype), jnp.cumsum(counts)[:-1]]
    )
    pos = jnp.arange(k * t) - offsets[se]
    keep = pos < capacity
    slot = se * capacity + jnp.clip(pos, 0, capacity - 1)
    return slot, st, sg, keep, aux


def _moe_sorted(
    config: MoEConfig,
    params: MoEParams,
    x: jax.Array,
    capacity: int,
    axis_name: Optional[str] = None,
) -> Tuple[jax.Array, jax.Array]:
    """moe_ffn body over sorted dispatch (see _routing_sorted)."""
    t, d = x.shape
    e = config.n_experts
    if axis_name is not None:
        ep = axis_size(axis_name)
        if (e // ep) * ep != e:
            # fail like the one-hot path does — not with an opaque
            # all_to_all split-axis shape error
            raise ValueError(
                f"n_experts {e} not divisible by ep={ep}"
            )
    slot, st, sg, keep, aux = _routing_sorted(config, params, x, capacity)
    rows = x[st].astype(config.dtype) * keep[:, None].astype(config.dtype)
    # dropped entries are zeroed BEFORE the scatter-add, so the
    # clipped slot they alias contributes nothing
    expert_in = jnp.zeros(
        (e * capacity, d), config.dtype
    ).at[slot].add(rows).reshape(e, capacity, d)
    if axis_name is None:
        expert_out = _expert_ffn(config, params, expert_in)
    else:
        aux = lax.pmean(aux, axis_name)
        # same wire pattern as the one-hot path: ship slots to the
        # expert owners, compute, ship back (tokens ride ICI while
        # the expert matmuls run)
        expert_in = lax.all_to_all(
            expert_in, axis_name, split_axis=0, concat_axis=1, tiled=True
        )
        expert_out = _expert_ffn(config, params, expert_in)
        expert_out = lax.all_to_all(
            expert_out, axis_name, split_axis=1, concat_axis=0, tiled=True
        )
    out_rows = expert_out.reshape(e * capacity, d)[slot]
    weight = (sg * keep).astype(jnp.float32)[:, None]
    y = jnp.zeros((t, d), jnp.float32).at[st].add(
        out_rows.astype(jnp.float32) * weight
    )
    return y.astype(x.dtype), aux


def _expert_ffn(config: MoEConfig, params: MoEParams, h: jax.Array) -> jax.Array:
    """h [E_local, slots, d] -> [E_local, slots, d]: batched SwiGLU.

    Expert weights may be weight-only int8 (models/quantize.py): the
    [e, d, f] layout contracts axis -2 exactly like the dense path, so
    the same per-output-channel dequant fuses into each einsum."""
    h = h.astype(config.dtype)
    gate = jax.nn.silu(
        jnp.einsum("ecd,edf->ecf", h, dq(params["w_gate"], config.dtype))
    )
    up = jnp.einsum("ecd,edf->ecf", h, dq(params["w_up"], config.dtype))
    return jnp.einsum(
        "ecf,efd->ecd", gate * up, dq(params["w_down"], config.dtype)
    )


def moe_ffn(
    config: MoEConfig,
    params: MoEParams,
    x: jax.Array,
    axis_name: Optional[str] = None,
    capacity: Optional[int] = None,
    impl: str = "onehot",
) -> Tuple[jax.Array, jax.Array]:
    """MoE FFN on x [tokens, d_model] -> (y, aux_loss).

    Without ``axis_name``: all experts local (single device).  With
    ``axis_name`` (inside shard_map): tokens are sharded over ep and
    each rank owns n_experts / ep_size experts — params' expert axis
    must be sharded over ep accordingly.

    ``capacity`` overrides the factor-derived per-expert slot count;
    decode passes capacity = tokens so NO token is ever dropped (slot
    competition is a training-time load-balancing pressure, not a
    serving behavior).

    ``impl`` picks the dispatch: "onehot" (dense [t,E,C] one-hot
    einsums — every op a matmul) or "sorted" (argsort + row
    gather/scatter — no O(t*E*C) tensors, preferred for large token
    groups).  Drop semantics are identical (choice-major priority);
    tests hold numeric agreement in the drop-free regime.
    """
    t, d = x.shape
    capacity = capacity if capacity is not None else config.capacity(t)
    if impl == "sorted":
        return _moe_sorted(config, params, x, capacity, axis_name)
    if axis_name is None:
        dispatch, combine, aux = _routing(config, params, x, capacity)
        # dispatch/combine matmuls run in the COMPUTE dtype: the
        # one-hot dispatch is exactly representable in bf16 and the
        # expert FFN consumes bf16 anyway.  Measured MFU-neutral on
        # v5e (XLA already folds the f32 convert into the matmul) —
        # kept for dtype consistency with the expert FFN, NOT as a
        # perf lever (r5 sweep notes in bench.py bench_moe).
        dt = config.dtype
        expert_in = jnp.einsum(
            "tec,td->ecd", dispatch.astype(dt), x.astype(dt)
        )
        expert_out = _expert_ffn(config, params, expert_in)
        y = jnp.einsum(
            "tec,ecd->td", combine.astype(dt), expert_out.astype(dt)
        )
        return y.astype(x.dtype), aux

    ep = axis_size(axis_name)
    e_local = config.n_experts // ep
    if e_local * ep != config.n_experts:
        raise ValueError(
            f"n_experts {config.n_experts} not divisible by ep={ep}"
        )
    # every rank routes its LOCAL tokens against the global router
    # (router weights replicated), then ships slots to expert owners
    dispatch, combine, aux = _routing(config, params, x, capacity)
    aux = lax.pmean(aux, axis_name)
    # same compute dtype as the single-device branch: the two paths
    # must not silently differ in precision
    dt = config.dtype
    expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(dt), x.astype(dt))
    # [E, C, d] -> [E/ep, ep*C, d]: each rank receives every other
    # rank's slots for the experts it owns
    expert_in = lax.all_to_all(
        expert_in, axis_name, split_axis=0, concat_axis=1, tiled=True
    )
    expert_out = _expert_ffn(config, params, expert_in)
    # reverse trip: [E/ep, ep*C, d] -> [E, C, d] back at the senders
    expert_out = lax.all_to_all(
        expert_out, axis_name, split_axis=1, concat_axis=0, tiled=True
    )
    y = jnp.einsum(
        "tec,ecd->td", combine.astype(dt), expert_out.astype(dt)
    )
    return y.astype(x.dtype), aux


def expert_shard_spec():
    """PartitionSpec rules for the param tree under ep sharding."""
    from jax.sharding import PartitionSpec as P

    return {
        "router": P(None, None),
        "w_gate": P("ep", None, None),
        "w_up": P("ep", None, None),
        "w_down": P("ep", None, None),
    }


def moe_sharding_rules(prefix: str = "", stacked: bool = False):
    """Param path -> PartitionSpec for the jit/GSPMD path: experts over
    ``ep``, then the scaling-book fsdp/tp split within each expert.

    This is the layout transformer.sharding_rules consumes for the MoE
    flagship (``stacked=True`` prepends the lax.scan layer axis);
    keeping it beside the dispatch code means a dispatch-layout change
    and its sharding change land in the same file.  The router stays
    fully replicated — routing logits are f32 and tiny, and every
    chip needs them before dispatch.
    """
    from jax.sharding import PartitionSpec as P

    lead = (None,) if stacked else ()
    return {
        f"{prefix}router": P(*lead, None, None),
        f"{prefix}w_gate": P(*lead, "ep", "fsdp", "tp"),
        f"{prefix}w_up": P(*lead, "ep", "fsdp", "tp"),
        f"{prefix}w_down": P(*lead, "ep", "tp", "fsdp"),
    }
