"""Slot-pool continuous-batching engine (model-agnostic half).

The dispatch-per-group serve loop ran one whole ``generate`` per
micro-batch: a request arriving one step after a dispatch started
waited the FULL previous generation before its prefill even began,
and every row padded out to the group's longest generation.  This
engine replaces that loop with per-step scheduling over a persistent
slot pool:

* the KV cache is allocated ONCE at ``SLOTS x max_len`` (static
  shapes — XLA never recompiles as occupancy changes);
* waiting requests are admitted into free slots at EVERY decode step
  (prefill-into-slot, models/decode.py), so p95 time-to-first-token is
  O(one decode tick + own prefill) instead of O(a whole generation);
* finished rows (per-row EOS / max-token / cache-exhausted) retire
  their slot IMMEDIATELY — the pool never pads a short answer out to
  the longest row, which is where the mean-to-max generation-length
  throughput win comes from (bench.py bench_continuous_serve).

The engine is model-agnostic and jax-free: the device half is two
injected callables (the single-chip server binds them straight to a
``serve.pool.PoolModel``; the gang driver wraps them in ADMIT/DECODE
broadcast ticks so every rank steps the same program).  Liveness
rules inherited from ``utils/microbatch.py`` (which this subsumes for
both servers): FIFO admission order, queue-timeout removal (abandoned
work never reaches the chip — an active abandoned row retires at the
next tick, freeing its slot early), and an ``on_idle`` hook so an
SPMD gang keeps meeting in collectives with no traffic.

Serving load telemetry: ``stats()`` reports queue depth, active
slots, KV occupancy, tokens/s and TTFT percentiles; ``
register_metrics`` exports the gauges through a metrics registry
(StatsD/Prometheus), and ``stats_path`` mirrors them to
``servestats.json`` in the task sandbox, where the scheduler's
``GET /v1/debug/serving`` collects them per pod — the load signal
ROADMAP item 2 names for scale-out decisions.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Callable, List, Optional, Sequence

import numpy as np

from dcos_commons_tpu.utils.microbatch import QueueTimeoutError

SERVESTATS_NAME = "servestats.json"
_TTFT_WINDOW = 512      # TTFT samples kept for the percentile gauges
_RATE_WINDOW_S = 10.0   # tokens/s sliding window


class _Group:
    """One ``submit()`` call: N rows answered together."""

    __slots__ = ("rows", "remaining", "done", "error", "abandoned")

    def __init__(self, rows: List["_Row"]):
        self.rows = rows
        self.remaining = len(rows)
        self.done = threading.Event()
        self.error: Optional[BaseException] = None
        self.abandoned = False


class _Row:
    """One prompt riding one KV slot."""

    __slots__ = (
        "tokens", "n", "temp", "eos", "seed", "out", "group",
        "arrival", "slot",
    )

    def __init__(self, tokens, n, temp, eos, seed, group):
        self.tokens = tokens
        self.n = n
        self.temp = temp
        self.eos = eos
        self.seed = seed
        self.out: List[int] = []
        self.group = group
        self.arrival = time.monotonic()
        self.slot = -1


class SlotEngine:
    """Admission loop over a persistent slot-pool KV cache.

    ``prefill_fn(padded [1, prompt_len] i32, slot=, true_len=, temp=,
    seed=) -> first token`` runs one prompt into a pool row (the
    scalars are passed by KEYWORD — transposing slot and true_len is
    a silent cache corruption);
    ``decode_fn(tok [S] i32, pos [S] i32, temps [S] f32, seeds [S]
    i32, n_active) -> next tokens [S] i32`` advances EVERY row one
    step (inactive rows are parked at slot state (0, 0) — their
    computation is discarded and their cache row is fully overwritten
    by the next admission's prefill).  Both run OUTSIDE the engine
    lock; only host-side bookkeeping holds it.
    """

    def __init__(
        self,
        prefill_fn: Callable,
        decode_fn: Callable,
        slots: int,
        max_len: int,
        prompt_len: int,
        queue_timeout_s: float = 600.0,
        on_idle: Optional[Callable[[], None]] = None,
        idle_every_s: float = 0.05,
        stats_path: Optional[str] = None,
        stats_every_s: float = 1.0,
        log: Optional[Callable[[str], None]] = None,
    ):
        if slots < 1:
            raise ValueError(f"slot pool needs >= 1 slot, got {slots}")
        self._prefill_fn = prefill_fn
        self._decode_fn = decode_fn
        self._slots = slots
        self._max_len = max_len
        self._prompt_len = prompt_len
        self._queue_timeout_s = queue_timeout_s
        self._on_idle = on_idle
        self._idle_every_s = idle_every_s
        self._stats_path = stats_path
        self._stats_every_s = stats_every_s
        self._log = log

        self._cv = threading.Condition()
        self._queue: deque = deque()
        self._rows: List[Optional[_Row]] = [None] * slots
        self._free = list(range(slots - 1, -1, -1))  # pop() -> slot 0 first
        self._active = 0
        self._tok = np.zeros(slots, np.int32)
        self._pos = np.zeros(slots, np.int32)
        self._temps = np.zeros(slots, np.float32)
        self._seeds = np.zeros(slots, np.int32)
        self._stopped = False
        # telemetry (counters under the cv; deques pruned on append)
        self._admitted = 0
        self._completed = 0
        self._timeouts = 0
        self._tokens_out = 0
        self._ttft: deque = deque(maxlen=_TTFT_WINDOW)
        self._rate: deque = deque()  # (monotonic, tokens) per tick
        self._merge_logged = False
        self._stats_written = 0.0  # loop-thread only
        self._thread = threading.Thread(
            target=self._loop, name="slot-engine", daemon=True
        )
        self._thread.start()

    # -- client surface ----------------------------------------------

    def submit(
        self,
        rows: Sequence[Sequence[int]],
        max_new_tokens: int,
        temperature: float = 0.0,
        eos_id: Optional[int] = None,
    ) -> List[List[int]]:
        """Queue ``rows`` (each its own slot, admitted independently
        as slots free up — a multi-row request may overlap several
        pool generations) and block until every row finished.  Raises
        ``QueueTimeoutError`` on saturation (handlers map it to 503),
        ``ValueError`` on caller error (400)."""
        if not rows:
            raise ValueError("tokens must be non-empty")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}"
            )
        for row in rows:
            if len(row) < 1:
                raise ValueError("prompts must be non-empty")
            if len(row) > self._prompt_len:
                raise ValueError(
                    f"prompt length {len(row)} exceeds the server's "
                    f"context {self._prompt_len}"
                )
            if len(row) + max_new_tokens > self._max_len:
                raise ValueError(
                    f"prompt {len(row)} + {max_new_tokens} new tokens "
                    f"cannot fit the {self._max_len}-position slot"
                )
        group = _Group([])
        group.rows = [
            _Row(
                [int(t) for t in row], max_new_tokens, float(temperature),
                eos_id,
                int.from_bytes(os.urandom(4), "little") % (2 ** 31),
                group,
            )
            for row in rows
        ]
        group.remaining = len(group.rows)
        with self._cv:
            self._queue.extend(group.rows)
            self._cv.notify_all()
        # the timeout bounds SATURATION, not a healthy generation: a
        # window with no row admitted (starved for a slot) or no new
        # token across the whole group (the pool stalled) abandons;
        # an admitted group that keeps producing is never cut off
        # mid-generation just for being long
        last_progress = -1
        while not group.done.wait(timeout=self._queue_timeout_s):
            with self._cv:
                admitted = any(r.slot >= 0 for r in group.rows)
                progress = sum(len(r.out) for r in group.rows)
                if admitted and progress > last_progress:
                    last_progress = progress
                    continue
                # abandoned work never reaches the chip: queued rows
                # leave the queue NOW; already-active rows retire at
                # the next tick, freeing their slots early instead of
                # decoding a dead request to completion
                group.abandoned = True
                self._queue = deque(
                    r for r in self._queue if r.group is not group
                )
                self._timeouts += 1
                reason = (
                    "request timed out waiting for a KV slot"
                    if not admitted else
                    f"no decode progress in {self._queue_timeout_s}s"
                )
            raise QueueTimeoutError(reason)
        if group.error is not None:
            raise group.error
        return [list(r.out) for r in group.rows]

    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            self._cv.notify_all()
        self._thread.join(timeout=10)

    # -- telemetry ---------------------------------------------------

    def stats(self) -> dict:
        """Serving-load snapshot (the per-pod gauges ROADMAP item 2
        names as the scale-out signal)."""
        now = time.monotonic()
        with self._cv:
            live_tokens = int(sum(
                int(self._pos[s])
                for s, row in enumerate(self._rows) if row is not None
            ))
            window = [n for (t, n) in self._rate
                      if t > now - _RATE_WINDOW_S]
            ttft = sorted(self._ttft)
            out = {
                "slots": self._slots,
                "max_len": self._max_len,
                "queue_depth": len(self._queue),
                "active_slots": self._active,
                "free_slots": len(self._free),
                "kv_live_tokens": live_tokens,
                "kv_occupancy": round(
                    live_tokens / float(self._slots * self._max_len), 4
                ),
                "tokens_per_s": round(
                    sum(window) / _RATE_WINDOW_S, 2
                ),
                "requests_admitted": self._admitted,
                "requests_completed": self._completed,
                "requests_timed_out": self._timeouts,
                "tokens_out": self._tokens_out,
            }
        if ttft:
            from dcos_commons_tpu.metrics.registry import percentile

            out["ttft_p50_s"] = round(percentile(ttft, 50), 4)
            out["ttft_p95_s"] = round(percentile(ttft, 95), 4)
        out["t"] = time.time()
        return out

    def register_metrics(self, metrics, prefix: str = "serving") -> None:
        """Export the load gauges through a metrics registry
        (metrics/registry.py): queue depth, active slots, KV
        occupancy, tokens/s — scraped as gauges / pushed via StatsD."""
        for key in ("queue_depth", "active_slots", "kv_occupancy",
                    "tokens_per_s"):
            metrics.gauge(
                f"{prefix}.{key}",
                lambda key=key: self.stats()[key],
            )

    # -- the loop ----------------------------------------------------

    def _loop(self) -> None:
        # persists across iterations: the on_idle servers (gang) pass
        # through the outer loop once per idle TICK, and the terminal
        # flush must happen once per idle PERIOD, not at 20 Hz forever
        flushed_idle = False
        while True:
            idle = False
            flush_now = False
            admits: List[_Row] = []
            with self._cv:
                while (not self._queue and self._active == 0
                       and not self._stopped):
                    if not flushed_idle:
                        # flush the terminal snapshot before parking:
                        # an idle server's LAST burst must be visible
                        # to /v1/debug/serving, not its second-to-last.
                        # The write itself happens OUTSIDE the lock —
                        # file IO on a slow sandbox must not block
                        # submit() callers needing the cv
                        flushed_idle = True
                        flush_now = True
                        break
                    if self._on_idle is None:
                        self._cv.wait()
                    else:
                        self._cv.wait(timeout=self._idle_every_s)
                        if not self._queue and self._active == 0:
                            break  # fire on_idle OUTSIDE the lock
                if self._stopped:
                    return
                idle = not self._queue and self._active == 0
                if not idle:
                    flushed_idle = False  # work resumed: re-arm
                    admits = self._pop_admits_locked()
            if flush_now:
                self._write_stats(force=True)
                continue
            if idle:
                self._safe_idle()
                continue
            try:
                self._admit_all(admits)
                if self._active:  # loop thread is the only writer
                    self._decode_tick()
                self._write_stats()
            except Exception as e:  # noqa: BLE001 — fail FAST, not silent
                # a bookkeeping bug (bad decode shape, broken stats
                # path) must not kill this thread silently: every
                # client would then block its full timeout and the
                # gang's followers would wedge in a stale collective.
                # Fan the error out and keep the loop alive.
                with self._cv:
                    self._fail_all_locked(e)

    def _pop_admits_locked(self) -> List[_Row]:
        """FIFO admission: oldest waiting rows take the free slots —
        a row can never starve behind later arrivals."""
        admits: List[_Row] = []
        while self._queue and self._free:
            row = self._queue.popleft()
            if row.group.abandoned:
                continue
            row.slot = self._free.pop()
            admits.append(row)
        return admits

    def _admit_all(self, admits: List[_Row]) -> None:
        for i, row in enumerate(admits):
            padded = np.zeros((1, self._prompt_len), np.int32)
            padded[0, : len(row.tokens)] = row.tokens
            try:
                first = int(self._prefill_fn(
                    padded, slot=row.slot, true_len=len(row.tokens),
                    temp=row.temp, seed=row.seed,
                ))
            except Exception as e:  # noqa: BLE001 — fan out, keep serving
                with self._cv:
                    # the popped-but-not-installed rows (this one and
                    # the rest of the batch) are invisible to both the
                    # queue and the active set: return their slots and
                    # fail their groups explicitly, or each failure
                    # would leak a slot and leave its client waiting
                    # out the full timeout for a model error
                    for r in admits[i:]:
                        self._free.append(r.slot)
                        r.slot = -1
                    self._fail_all_locked(
                        e, extra_groups={r.group for r in admits[i:]}
                    )
                return
            now = time.monotonic()
            with self._cv:
                self._apply_admit_locked(row, first, now)

    def _apply_admit_locked(self, row: _Row, first: int, now: float):
        self._admitted += 1
        self._ttft.append(now - row.arrival)
        row.out.append(first)
        self._count_tokens_locked(1, now)
        if self._row_finished(row, first, int(len(row.tokens))):
            self._retire_locked(row)
            return
        slot = row.slot
        self._rows[slot] = row
        self._active += 1
        self._tok[slot] = first
        self._pos[slot] = len(row.tokens)  # next cache write position
        self._temps[slot] = row.temp
        self._seeds[slot] = row.seed

    def _decode_tick(self) -> None:
        active = self._active
        try:
            nxt = np.asarray(self._decode_fn(
                self._tok.copy(), self._pos.copy(),
                self._temps.copy(), self._seeds.copy(), active,
            ))
        except Exception as e:  # noqa: BLE001 — fan out, keep serving
            with self._cv:
                self._fail_all_locked(e)
            return
        now = time.monotonic()
        merged = None
        with self._cv:
            self._apply_decode_locked(nxt, now)
            if self._active >= 2 and not self._merge_logged:
                self._merge_logged = True
                merged = self._active
            elif self._active <= 1:
                self._merge_logged = False
        if merged is not None and self._log is not None:
            self._log(
                f"continuous-batch: {merged} rows sharing one decode "
                "step over the slot pool"
            )

    def _apply_decode_locked(self, nxt: np.ndarray, now: float) -> None:
        produced = 0
        for slot in range(self._slots):
            row = self._rows[slot]
            if row is None:
                continue
            if row.group.abandoned:
                self._retire_locked(row)
                continue
            token = int(nxt[slot])
            row.out.append(token)
            produced += 1
            self._pos[slot] += 1
            self._tok[slot] = token
            if (self._row_finished(row, token, int(self._pos[slot]))):
                self._retire_locked(row)
        self._count_tokens_locked(produced, now)

    def _row_finished(self, row: _Row, token: int, pos: int) -> bool:
        return (
            len(row.out) >= row.n
            or (row.eos is not None and token == row.eos)
            or pos >= self._max_len  # slot cache exhausted
        )

    def _retire_locked(self, row: _Row) -> None:
        slot = row.slot
        if self._rows[slot] is row:
            self._rows[slot] = None
            self._active -= 1
            self._tok[slot] = 0
            self._pos[slot] = 0
            self._temps[slot] = 0.0
            self._seeds[slot] = 0
        self._free.append(slot)
        group = row.group
        group.remaining -= 1
        if group.remaining <= 0 and not group.abandoned:
            self._completed += 1
            group.done.set()

    def _fail_all_locked(
        self, error: BaseException, extra_groups=(),
    ) -> None:
        """A model-call failure fans out to every waiting and active
        request (the MicroBatcher contract) and clears the pool.
        ``extra_groups``: groups of rows in admission limbo (popped
        from the queue, not yet installed in the pool) — the caller
        has already returned their slots."""
        groups = {r.group for r in self._queue}
        groups |= {r.group for r in self._rows if r is not None}
        groups |= set(extra_groups)
        self._queue.clear()
        for slot, row in enumerate(self._rows):
            if row is not None:
                self._rows[slot] = None
                self._active -= 1
                self._free.append(slot)
        self._tok[:] = 0
        self._pos[:] = 0
        self._temps[:] = 0.0
        self._seeds[:] = 0
        for group in groups:
            group.error = error
            group.done.set()

    def _count_tokens_locked(self, n: int, now: float) -> None:
        if n <= 0:
            return
        self._tokens_out += n
        self._rate.append((now, n))
        while self._rate and self._rate[0][0] < now - _RATE_WINDOW_S:
            self._rate.popleft()

    def _safe_idle(self) -> None:
        try:
            self._on_idle()
        except Exception:  # noqa: BLE001, sdklint: disable=swallowed-exception — idle hook must not kill serving
            pass

    def _write_stats(self, force: bool = False) -> None:
        """Mirror the gauges to the sandbox (loop thread only): the
        scheduler's /v1/debug/serving reads this per task."""
        if self._stats_path is None:
            return
        now = time.monotonic()
        if not force and now - self._stats_written < self._stats_every_s:
            return
        self._stats_written = now
        try:
            tmp = self._stats_path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(self.stats(), f)
            os.replace(tmp, self._stats_path)
        except OSError:
            pass  # sdklint: disable=swallowed-exception — telemetry must never take the server down


def read_servestats(path: str) -> dict:
    """Parse a worker's servestats.json; {} when absent/corrupt (a
    worker killed mid-replace leaves the previous snapshot or none)."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError):
        return {}
    return data if isinstance(data, dict) else {}
