"""Secret providers: where secret refs in a ServiceSpec resolve from.

Reference: dcos/clients/SecretsClient.java — the reference fetches
secret values from the DC/OS secrets service by path.  Here the
scheduler resolves each ref through a pluggable provider at launch
time and ships the VALUE to the agent as a 0600 sandbox file or an
env var; the value never touches the state store, logs, or the
artifacts endpoint.
"""

from __future__ import annotations

import os
from abc import ABC, abstractmethod
from typing import Dict


class SecretNotFound(Exception):
    def __init__(self, source: str):
        super().__init__(f"secret not found: {source!r}")
        self.source = source


class SecretsProvider(ABC):
    @abstractmethod
    def fetch(self, source: str) -> bytes:
        """Value for ``source``; raises SecretNotFound."""


class FileSecretsProvider(SecretsProvider):
    """Secrets from an operator-managed directory tree: the secret ref
    ``app/password`` reads ``<root>/app/password``.  Path traversal in
    refs is rejected."""

    def __init__(self, root: str):
        self._root = os.path.realpath(root)

    def fetch(self, source: str) -> bytes:
        path = os.path.realpath(os.path.join(self._root, source.lstrip("/")))
        if not path.startswith(self._root + os.sep):
            raise SecretNotFound(source)
        try:
            with open(path, "rb") as f:
                return f.read()
        except OSError:
            raise SecretNotFound(source)


class InMemorySecretsProvider(SecretsProvider):
    """Tests / sim harness."""

    def __init__(self, values: Dict[str, bytes]):
        self._values = dict(values)

    def fetch(self, source: str) -> bytes:
        try:
            return self._values[source]
        except KeyError:
            raise SecretNotFound(source)
