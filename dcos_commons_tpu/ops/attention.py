"""Blocked flash attention for one device — forward AND backward.

MXU-first design (pallas_guide.md): Q blocks stream through a grid of
(batch*heads, q_blocks); K/V live in VMEM per grid cell and the kernel
walks K blocks with an online-softmax accumulator, so the [S, S] score
matrix never materializes in HBM.  bf16 in, f32 accumulation,
``preferred_element_type`` on every dot.

The backward pass is the FlashAttention-2 recurrence in two kernels:
a dq kernel gridded like the forward (stream K blocks per Q block) and
a dk/dv kernel gridded over K blocks (stream Q blocks), both driven by
the logsumexp residual the forward saves per row.  The residual rides
in a [rows, 128] tile (value replicated across the minor dim) because
Mosaic wants lane-width minor dimensions.

For sequences sharded across devices use
dcos_commons_tpu.parallel.ring.ring_attention, which applies the same
accumulation across ring hops.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

_NEG = -1e30
_LANES = 128  # residual tile minor dim (Mosaic layout requirement)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k: int,
                causal: bool):
    from jax.experimental import pallas as pl

    q_index = pl.program_id(1)
    block_q = q_ref.shape[0]
    head_dim = q_ref.shape[1]
    seq_k = k_ref.shape[0]
    scale = head_dim ** -0.5

    # MXU rate note: operands stay in the INPUT dtype (bf16) with f32
    # accumulation — casting q/k/v to f32 before the dots would run the
    # systolic array at the f32 rate, HALF the bf16 rate (measured 31
    # vs 60+ TF/s fwd on v5e at these shapes).  The scale is applied to
    # the f32 scores, not the bf16 operands, so no precision is lost.
    q = q_ref[:]
    m = jnp.full((block_q, 1), _NEG, jnp.float32)
    l = jnp.zeros((block_q, 1), jnp.float32)
    acc = jnp.zeros((block_q, head_dim), jnp.float32)

    q_pos = q_index * block_q + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    k_off = lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)

    def make_body(masked: bool):
        def body(j, carry):
            m, l, acc = carry
            from jax.experimental import pallas as pl  # noqa: trace-local

            k = k_ref[pl.ds(j * block_k, block_k), :]
            v = v_ref[pl.ds(j * block_k, block_k), :]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale
            if masked:
                valid = q_pos >= (j * block_k + k_off)
                s = jnp.where(valid, s, _NEG)
            m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)
            if masked:
                p = jnp.where(valid, p, 0.0)
            alpha = jnp.exp(m - m_new)
            l_new = l * alpha + p.sum(axis=-1, keepdims=True)
            # p in [0,1] downcast to the value dtype for the MXU; the
            # f32 accumulator keeps the summation exact
            acc_new = acc * alpha + jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            return m_new, l_new, acc_new

        return body

    # single loop with in-body masking measured FASTER than splitting
    # into an unmasked phase + a diagonal phase (two fori_loops cost
    # more than the mask VPU ops they save); K blocks fully in the
    # future are still skipped via the loop bound
    if causal:
        n_blocks = jnp.minimum(
            pl.cdiv((q_index + 1) * block_q, block_k), seq_k // block_k
        )
    else:
        n_blocks = seq_k // block_k
    m, l, acc = lax.fori_loop(
        0, n_blocks, make_body(masked=causal), (m, l, acc)
    )
    l = jnp.maximum(l, 1e-30)
    o_ref[:] = (acc / l).astype(o_ref.dtype)
    if lse_ref is not None:
        lse = m + jnp.log(l)
        lse_ref[:] = jnp.broadcast_to(lse, (block_q, _LANES))


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, di_ref, dq_ref, *,
               block_k: int, causal: bool):
    """dq for one Q block: stream K blocks (FA2 eq.: ds = p*(dp - di),
    dq = scale * ds @ k)."""
    from jax.experimental import pallas as pl

    q_index = pl.program_id(1)
    block_q = q_ref.shape[0]
    head_dim = q_ref.shape[1]
    seq_k = k_ref.shape[0]
    scale = head_dim ** -0.5

    # bf16 operands + f32 accumulation throughout (see _fwd_kernel's
    # MXU rate note); the score scale is applied to f32 s, and ds is
    # downcast for its MXU dot — ds = p*(dp-di) with p in [0,1]
    q = q_ref[:]
    do = do_ref[:]
    lse = lse_ref[:, :1]
    di = di_ref[:, :1]
    acc = jnp.zeros((block_q, head_dim), jnp.float32)

    q_pos = q_index * block_q + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    k_off = lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)

    def make_body(masked: bool):
        def body(j, acc):
            from jax.experimental import pallas as pl  # noqa: trace-local

            k = k_ref[pl.ds(j * block_k, block_k), :]
            v = v_ref[pl.ds(j * block_k, block_k), :]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale
            if masked:
                valid = q_pos >= (j * block_k + k_off)
                s = jnp.where(valid, s, _NEG)
            p = jnp.exp(s - lse)
            if masked:
                p = jnp.where(valid, p, 0.0)
            dp = jax.lax.dot_general(
                do, v, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            ds = p * (dp - di)
            return acc + jax.lax.dot_general(
                ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

        return body

    if causal:
        n_blocks = jnp.minimum(
            pl.cdiv((q_index + 1) * block_q, block_k), seq_k // block_k
        )
    else:
        n_blocks = seq_k // block_k
    acc = lax.fori_loop(0, n_blocks, make_body(masked=causal), acc)
    dq_ref[:] = (acc * scale).astype(dq_ref.dtype)


def _dkv_kernel(k_ref, v_ref, q_ref, do_ref, lse_ref, di_ref,
                dk_ref, dv_ref, *, block_q: int, causal: bool):
    """dk/dv for one K block: stream Q blocks (dv = p^T @ do,
    dk = scale * ds^T @ q)."""
    from jax.experimental import pallas as pl

    k_index = pl.program_id(1)
    block_k = k_ref.shape[0]
    head_dim = k_ref.shape[1]
    seq_q = q_ref.shape[0]
    scale = head_dim ** -0.5

    # bf16 operands + f32 accumulation (see _fwd_kernel's MXU rate
    # note).  q is streamed UNSCALED so its bf16 bits are the caller's;
    # the scale lands once on f32 s and once on the final dk.
    k = k_ref[:]
    v = v_ref[:]
    dk = jnp.zeros((block_k, head_dim), jnp.float32)
    dv = jnp.zeros((block_k, head_dim), jnp.float32)

    k_pos = k_index * block_k + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    q_off = lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    def make_body(masked: bool):
        def body(i, carry):
            dk, dv = carry
            from jax.experimental import pallas as pl  # noqa: trace-local

            q = q_ref[pl.ds(i * block_q, block_q), :]
            do = do_ref[pl.ds(i * block_q, block_q), :]
            lse = lse_ref[pl.ds(i * block_q, block_q), :1]
            di = di_ref[pl.ds(i * block_q, block_q), :1]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale
            if masked:
                valid = (i * block_q + q_off) >= k_pos
                s = jnp.where(valid, s, _NEG)
            p = jnp.exp(s - lse)
            if masked:
                p = jnp.where(valid, p, 0.0)
            dv_new = dv + jax.lax.dot_general(
                p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            dp = jax.lax.dot_general(
                do, v, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            ds = p * (dp - di)
            dk_new = dk + jax.lax.dot_general(
                ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            return dk_new, dv_new

        return body

    if causal:
        # Q blocks strictly before this K block see none of it
        i_start = (k_index * block_k) // block_q
    else:
        i_start = 0
    dk, dv = lax.fori_loop(
        i_start, seq_q // block_q, make_body(masked=causal), (dk, dv)
    )
    # q was streamed unscaled, so dk takes the single scale factor here
    dk_ref[:] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "block_q", "block_k", "interpret",
                     "save_residuals"),
)
def _pallas_attention(q, k, v, causal, block_q, block_k, interpret,
                      save_residuals=False):
    from jax.experimental import pallas as pl

    batch, heads, seq_q, head_dim = q.shape
    seq_k = k.shape[2]
    bh = batch * heads
    qr = q.reshape(bh, seq_q, head_dim)
    kr = k.reshape(bh, seq_k, head_dim)
    vr = v.reshape(bh, seq_k, head_dim)
    grid = (bh, seq_q // block_q)
    out_shape = [jax.ShapeDtypeStruct(qr.shape, q.dtype)]
    out_specs = [
        pl.BlockSpec((None, block_q, head_dim), lambda b, i: (b, i, 0))
    ]
    if save_residuals:
        out_shape.append(
            jax.ShapeDtypeStruct((bh, seq_q, _LANES), jnp.float32)
        )
        out_specs.append(
            pl.BlockSpec((None, block_q, _LANES), lambda b, i: (b, i, 0))
        )
        kernel = functools.partial(
            _fwd_kernel, block_k=block_k, causal=causal
        )
    else:
        kernel = functools.partial(
            lambda *refs, **kw: _fwd_kernel(*refs, None, **kw),
            block_k=block_k, causal=causal,
        )
    result = pl.pallas_call(
        kernel,
        out_shape=out_shape,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, head_dim), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, seq_k, head_dim), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, seq_k, head_dim), lambda b, i: (b, 0, 0)),
        ],
        out_specs=out_specs,
        interpret=interpret,
    )(qr, kr, vr)
    out = result[0].reshape(batch, heads, seq_q, head_dim)
    if save_residuals:
        return out, result[1]
    return out


@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret")
)
def _pallas_attention_bwd(q, k, v, o, lse, do, causal, block_q, block_k,
                          interpret):
    from jax.experimental import pallas as pl

    batch, heads, seq_q, head_dim = q.shape
    seq_k = k.shape[2]
    bh = batch * heads
    qr = q.reshape(bh, seq_q, head_dim)
    kr = k.reshape(bh, seq_k, head_dim)
    vr = v.reshape(bh, seq_k, head_dim)
    dor = do.reshape(bh, seq_q, head_dim)
    # di = rowsum(do * o): cheap elementwise reduce, then lane-tiled to
    # match the residual layout
    di = jnp.sum(
        dor.astype(jnp.float32)
        * o.reshape(bh, seq_q, head_dim).astype(jnp.float32),
        axis=-1,
    )
    di = jnp.broadcast_to(di[..., None], (bh, seq_q, _LANES))

    row_spec = pl.BlockSpec((None, block_q, head_dim), lambda b, i: (b, i, 0))
    lane_spec = pl.BlockSpec((None, block_q, _LANES), lambda b, i: (b, i, 0))
    full = lambda seq: pl.BlockSpec(
        (None, seq, head_dim), lambda b, i: (b, 0, 0)
    )
    full_lanes = pl.BlockSpec((None, seq_q, _LANES), lambda b, i: (b, 0, 0))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, block_k=block_k, causal=causal),
        out_shape=jax.ShapeDtypeStruct(qr.shape, q.dtype),
        grid=(bh, seq_q // block_q),
        in_specs=[row_spec, full(seq_k), full(seq_k), row_spec, lane_spec,
                  lane_spec],
        out_specs=row_spec,
        interpret=interpret,
    )(qr, kr, vr, dor, lse, di)

    kcol_spec = pl.BlockSpec((None, block_k, head_dim), lambda b, j: (b, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, block_q=block_q, causal=causal),
        out_shape=[
            jax.ShapeDtypeStruct(kr.shape, k.dtype),
            jax.ShapeDtypeStruct(vr.shape, v.dtype),
        ],
        grid=(bh, seq_k // block_k),
        in_specs=[kcol_spec, kcol_spec, full(seq_q), full(seq_q),
                  full_lanes, full_lanes],
        out_specs=[kcol_spec, kcol_spec],
        interpret=interpret,
    )(kr, vr, qr, dor, lse, di)

    shape = (batch, heads, seq_q, head_dim)
    kshape = (batch, heads, seq_k, head_dim)
    return dq.reshape(shape), dk.reshape(kshape), dv.reshape(kshape)


def _dispatch_pallas(q, k, block_q, block_k, force_pallas, interpret) -> bool:
    """Single source of truth for the kernel-vs-reference choice: the
    primal and the residual-saving forward must always agree."""
    seq_q, seq_k = q.shape[2], k.shape[2]
    use_pallas = force_pallas or interpret or jax.default_backend() == "tpu"
    tiles = seq_q % block_q == 0 and seq_k % block_k == 0
    return use_pallas and tiles


def _impl(q, k, v, causal, block_q, block_k, force_pallas, interpret):
    if _dispatch_pallas(q, k, block_q, block_k, force_pallas, interpret):
        return _pallas_attention(q, k, v, causal, block_q, block_k, interpret)
    from dcos_commons_tpu.parallel.ring import reference_attention

    return reference_attention(q, k, v, causal)


@functools.lru_cache(maxsize=None)
def _make_attention(causal, block_q, block_k, force_pallas, interpret):
    """Per-config differentiable attention: Pallas forward AND backward
    (FlashAttention-2 two-kernel recurrence over the saved logsumexp).
    Shapes that don't tile fall back to the dense reference both ways.
    """
    from dcos_commons_tpu.parallel.ring import reference_attention

    @jax.custom_vjp
    def attn(q, k, v):
        return _impl(q, k, v, causal, block_q, block_k, force_pallas, interpret)

    def fwd(q, k, v):
        if _dispatch_pallas(q, k, block_q, block_k, force_pallas, interpret):
            o, lse = _pallas_attention(
                q, k, v, causal, block_q, block_k, interpret,
                save_residuals=True,
            )
            return o, (q, k, v, o, lse)
        return attn(q, k, v), (q, k, v, None, None)

    def bwd(residuals, g):
        q, k, v, o, lse = residuals
        if lse is not None:
            return _pallas_attention_bwd(
                q, k, v, o, lse, g, causal, block_q, block_k, interpret
            )
        _, vjp = jax.vjp(
            lambda q_, k_, v_: reference_attention(q_, k_, v_, causal), q, k, v
        )
        return vjp(g)

    attn.defvjp(fwd, bwd)
    return attn


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    block_q: int = 128,
    block_k: int = 128,
    force_pallas: bool = False,
    interpret: bool = False,
) -> jax.Array:
    """[batch, heads, seq, head_dim] attention, differentiable.

    Dispatch: Pallas kernels on TPU (or when forced / interpreted for
    tests); jnp reference otherwise.  Falls back when shapes do not
    tile (ragged seq), keeping the call always-correct.
    """
    return _make_attention(causal, block_q, block_k, force_pallas, interpret)(
        q, k, v
    )
