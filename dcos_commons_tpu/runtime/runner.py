"""FrameworkRunner: the scheduler-process entrypoint.

Reference: framework/FrameworkRunner.java:90 (registerAndRunFramework)
+ scheduler/SchedulerRunner.java:82-101 (lock -> metrics -> build ->
run) + curator/CuratorLocker.java (single-instance mutex).  This is
what makes the framework startable as a *service*:

    python -m dcos_commons_tpu serve svc.yml --topology cluster.yml

takes an exclusive file lock on the state directory (two schedulers
over one state store corrupt plans — the CuratorLocker's job), loads
the fleet topology, connects the per-host agent daemons, starts the
API server BEFORE the event loop accepts work (FrameworkRunner.java:
130-138), and runs until stopped or wedged.

Exit codes (reference: framework/ProcessExit.java):
    0  uninstall completed / clean stop
    2  scheduler wedged (fatal_error set by run_forever)
    3  another scheduler instance holds the lock
    4  invalid configuration
"""

from __future__ import annotations

import fcntl
import logging
import os
import signal
import sys
import threading
from typing import Dict, List, Optional, Tuple

import yaml

from dcos_commons_tpu.offer.inventory import SliceInventory, TpuHost
from dcos_commons_tpu.scheduler.builder import SchedulerBuilder
from dcos_commons_tpu.scheduler.config import SchedulerConfig

LOG = logging.getLogger(__name__)

EXIT_WEDGED = 2
EXIT_LOCKED = 3
EXIT_BAD_CONFIG = 4


class InstanceLock:
    """Exclusive advisory lock: one scheduler per state directory.

    Reference: curator/CuratorLocker.java — taken in
    SchedulerRunner.run() before anything touches the state store."""

    def __init__(self, state_dir: str):
        os.makedirs(state_dir, exist_ok=True)
        self._path = os.path.join(state_dir, "scheduler.lock")
        self._fd: Optional[int] = None

    def acquire(self) -> bool:
        fd = os.open(self._path, os.O_CREAT | os.O_RDWR, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            os.close(fd)
            return False
        os.ftruncate(fd, 0)
        os.write(fd, str(os.getpid()).encode())
        self._fd = fd
        return True

    def release(self) -> None:
        if self._fd is not None:
            fcntl.flock(self._fd, fcntl.LOCK_UN)
            os.close(self._fd)
            self._fd = None


def make_instance_lock(config: SchedulerConfig, name: str):
    """One active scheduler per service: a TTL lease on the state
    server when remote state is configured (failover-capable), else a
    per-host file lock (reference: CuratorLocker vs local mutex).

    With ``SDK_HA`` set (``config.ha_enabled``) the lease upgrades to
    a LEADER ELECTION: ``acquire`` candidates — blocking until the
    current leader's lease expires — instead of exiting, and the
    lease's fencing epoch is wired through the builder so a deposed
    leader's store writes are rejected (dcos_commons_tpu/ha/)."""
    if config.state_url:
        import socket as _socket

        owner = f"{_socket.gethostname()}-{os.getpid()}"
        if config.ha_enabled:
            from dcos_commons_tpu.ha.election import LeaderLock
            from dcos_commons_tpu.storage.remote import RemotePersister

            return LeaderLock(
                RemotePersister(
                    config.state_url,
                    auth_token=config.auth_token,
                    ca_file=config.tls_ca_file,
                ),
                name=name,
                owner=owner,
                ttl_s=config.state_lease_ttl_s,
            )
        from dcos_commons_tpu.storage.remote import RemoteLocker

        return RemoteLocker(
            config.state_url,
            name=name,
            owner=owner,
            ttl_s=config.state_lease_ttl_s,
            auth_token=config.auth_token,
            ca_file=config.tls_ca_file,
        )
    return InstanceLock(config.state_dir)


def load_topology(path: str) -> Tuple[List[TpuHost], Dict[str, str]]:
    """Parse a fleet topology YAML into hosts + agent-daemon URLs.

    Format (one entry per TPU-VM host)::

        hosts:
          - host_id: pod-0-h0-0
            agent_url: http://10.0.0.1:8476   # omit for in-process mode
            slice_id: pod-0
            generation: v5e
            grid: [0, 0]
            chip_block: [2, 2]
            cpus: 16
            memory_mb: 65536
            zone: z0

    Every host must either have an ``agent_url`` (remote fleet) or none
    may (single-process local mode) — mixing the two would leave some
    placements unlaunchable.
    """
    with open(path) as f:
        raw = yaml.safe_load(f) or {}
    hosts: List[TpuHost] = []
    urls: Dict[str, str] = {}
    for entry in raw.get("hosts", []):
        entry = dict(entry)
        url = entry.pop("agent_url", "")
        host = TpuHost(
            host_id=entry["host_id"],
            hostname=entry.get("hostname", ""),
            slice_id=entry.get("slice_id", ""),
            generation=entry.get("generation", ""),
            grid=tuple(entry.get("grid", (0, 0))),
            chip_block=tuple(entry.get("chip_block", (0, 0))),
            cpus=float(entry.get("cpus", 8.0)),
            memory_mb=int(entry.get("memory_mb", 16384)),
            disk_mb=int(entry.get("disk_mb", 102400)),
            attributes=dict(entry.get("attributes", {})),
            zone=entry.get("zone", ""),
            region=entry.get("region", ""),
        )
        hosts.append(host)
        if url:
            urls[host.host_id] = url
    if not hosts:
        raise ValueError(f"topology {path} defines no hosts")
    if urls and len(urls) != len(hosts):
        missing = [h.host_id for h in hosts if h.host_id not in urls]
        raise ValueError(
            f"topology mixes remote and local hosts; no agent_url for: "
            f"{missing}"
        )
    return hosts, urls


from dcos_commons_tpu.state.config_store import OptionsStore

OPTIONS_NODE = OptionsStore.NODE


class FrameworkRunner:
    """Build and run one service scheduler as a long-lived process."""

    def __init__(
        self,
        spec,
        config: Optional[SchedulerConfig] = None,
        topology_hosts: Optional[List[TpuHost]] = None,
        agent_urls: Optional[Dict[str, str]] = None,
        builder_hook=None,
        spec_source: Optional[Tuple[str, Dict[str, str]]] = None,
    ):
        self.spec = spec
        self.config = config or SchedulerConfig.from_env()
        self.topology_hosts = topology_hosts or []
        self.agent_urls = agent_urls or {}
        # (svc_yml_path, base_env): when present the runner can
        # RE-RENDER the spec with new option env — the live `update`
        # flow (reference: Cosmos update pushing new options to a
        # running scheduler, cli/commands.go:39,56).  Applied overrides
        # persist in the state tree so restarts/failovers keep them.
        self.spec_source = spec_source
        # hook(builder, spec): framework-specific wiring (recovery
        # overriders, plan customizers) — the Main.java analogue
        self.builder_hook = builder_hook
        # framework-specific HTTP endpoints (reference: Cassandra's
        # SeedsResource): routes_hook(scheduler) -> [(method, pattern,
        # handler(match, query))], called after build so handlers can
        # close over the live scheduler
        self.routes_hook = None
        self._lock = make_instance_lock(
            self.config, f"scheduler-{spec.name}"
        )
        self.scheduler = None
        self.api_server = None
        self.fleet = None
        # when set, '<url>' is written here once the API is listening
        # (lets launchers discover an ephemeral port)
        self.announce_file: str = ""
        # API bind address; 127.0.0.1 suits single-machine fleets, a
        # real multi-host deployment binds 0.0.0.0 (or the DCN address)
        self.api_bind: str = "127.0.0.1"
        # externally-reachable URL agents use to pull /v1/artifacts;
        # REQUIRED for remote fleets not on this machine — the default
        # (the server's own loopback URL) is meaningless on other hosts
        self.advertise_url: str = ""
        self._stop_requested = threading.Event()
        self._reload_requested = threading.Event()
        self._lease_lost: Optional[str] = None
        self._persister = None
        self._inventory = None
        self._agent = None
        # serializes update_options' read-merge-write of the options
        # node (ThreadingHTTPServer handles requests concurrently)
        self._update_lock = threading.Lock()
        # pre-update options node value, kept so a failed rebuild can
        # un-poison the store.  _options_dirty gates it: the snapshot
        # is taken only when no rollback is already pending (stacked
        # updates must roll back to the last BUILD-VALIDATED value,
        # not an unvalidated intermediate), and cleared only under
        # _update_lock once a rebuild succeeds with no update pending.
        self._options_rollback: Optional[bytes] = None
        self._options_dirty = False
        self._wire_lease_loss()

    def _wire_lease_loss(self) -> None:
        """Lease loss is fatal (reference: CuratorLocker exits the
        process on ZK lock loss) — a second active scheduler over the
        same state tree corrupts plans, so stop immediately."""
        if not hasattr(self._lock, "on_lost"):
            return

        def on_lost(reason: str) -> None:
            LOG.critical("instance lease lost: %s — stopping", reason)
            self._lease_lost = reason
            self.stop()

        self._lock.on_lost = on_lost

    # -- assembly -----------------------------------------------------

    def _build_infra(self) -> None:
        """Inventory, agent fleet, and persister live for the whole
        process — a live options update rebuilds only the scheduler
        over them (daemon connections and running sandboxes survive)."""
        if self._inventory is not None:
            return
        from dcos_commons_tpu.scheduler.builder import make_persister

        self._inventory = SliceInventory(self.topology_hosts)
        if self.agent_urls:
            from dcos_commons_tpu.agent.remote import RemoteFleet

            fleet = RemoteFleet(
                on_host_down=self._inventory.mark_down,
                on_host_up=self._inventory.mark_up,
                auth_token=self.config.auth_token,
                ca_file=self.config.tls_ca_file,
            )
            for host_id, url in self.agent_urls.items():
                fleet.add_host(host_id, url)
            self._agent = fleet
            self.fleet = fleet
        else:
            from dcos_commons_tpu.agent.local import LocalProcessAgent

            self._agent = LocalProcessAgent(self.config.sandbox_root)
        self._persister = make_persister(self.config)
        lease = getattr(self._lock, "lease", None)
        if lease is not None:
            from dcos_commons_tpu.ha.election import FencedPersister

            # the runner's own writes (options update/rollback) must
            # be lease-fenced too, not just the builder-wired stores —
            # a deposed leader's in-flight update would otherwise
            # clobber its successor's options
            self._persister = FencedPersister(self._persister, lease)

    def _stored_options(self) -> Dict[str, str]:
        return OptionsStore(self._persister).fetch()

    def _render_spec(self, overrides: Dict[str, str]):
        """Re-render svc.yml with base env + option overrides."""
        from dcos_commons_tpu.specification.yaml_spec import from_yaml_file

        yaml_path, base_env = self.spec_source
        env = dict(base_env)
        env.update(overrides)
        return from_yaml_file(yaml_path, env)

    def build(self) -> None:
        self._build_infra()
        if self.spec_source is not None:
            overrides = self._stored_options()
            if overrides:
                LOG.info(
                    "applying %d persisted option override(s): %s",
                    len(overrides), sorted(overrides),
                )
            self.spec = self._render_spec(overrides)
        builder = SchedulerBuilder(
            self.spec, self.config, persister=self._persister
        )
        builder.set_inventory(self._inventory)
        builder.set_agent(self._agent)
        lease = getattr(self._lock, "lease", None)
        if lease is not None:
            # HA mode: every store mutation is lease-fenced, and the
            # scheduler carries its HAState (gauges + /v1/debug/ha)
            builder.set_leader_lease(lease)
        if self.builder_hook is not None:
            self.builder_hook(builder, self.spec)
        self.scheduler = builder.build()

    # -- live options update (reference: Cosmos `update` flow) --------

    def update_options(self, env: Dict[str, str]):
        """Validate + persist new option env, then rebuild the
        scheduler in-process; returns an HTTP (code, body) pair.

        Reference: the Cosmos package `update` + CLI update section
        (cli/commands.go:39,56) push new options to a RUNNING
        scheduler; the rolling update then proceeds under the new
        target config exactly as a restart-with-new-env would."""
        if self.spec_source is None:
            return 409, {
                "message": "scheduler was not started from a YAML source; "
                           "live update is unavailable"
            }
        if not isinstance(env, dict) or not env or not all(
            isinstance(k, str) and isinstance(v, str) for k, v in env.items()
        ):
            return 400, {"message": "body must be {\"env\": {str: str}}"}
        with self._update_lock:
            return self._update_options_locked(env)

    def _update_options_locked(self, env: Dict[str, str]):
        from dcos_commons_tpu.specification.validation import (
            ConfigValidationError,
            ValidationContext,
            validate_spec_change,
        )

        merged = self._stored_options()
        merged.update(env)
        try:
            new_spec = self._render_spec(merged)
        except Exception as e:
            return 400, {"message": f"spec render failed: {e}"}
        scheduler = self.scheduler
        old_spec = None
        if scheduler is not None and scheduler.config_store is not None:
            target = scheduler.config_store.get_target_config()
            if target:
                data = scheduler.config_store.fetch(target)
                if data is not None:
                    from dcos_commons_tpu.specification.specs import (
                        ServiceSpec,
                    )

                    old_spec = ServiceSpec.from_dict(data)
        try:
            validate_spec_change(
                old_spec,
                new_spec,
                context=ValidationContext(
                    deployment_completed=(
                        scheduler.state_store.deployment_was_completed()
                        if scheduler is not None else None
                    ),
                    # a provider may also be wired programmatically
                    # (builder_hook -> set_secrets_provider); the built
                    # scheduler carries it
                    secrets_provider_present=(
                        bool(self.config.secrets_dir)
                        or getattr(scheduler, "secrets_provider", None)
                        is not None
                    ),
                    auth_token_present=(
                        bool(self.config.auth_token)
                        if self.agent_urls else None
                    ),
                ),
            )
        except ConfigValidationError as e:
            return 400, {"message": "invalid update", "errors": e.errors}
        # remember the pre-update node so a rebuild failure (build()
        # can fail for non-validation reasons) can roll it back —
        # otherwise the poisoned overrides re-apply and re-fail on
        # every restart.  Only the FIRST update since the last
        # successful rebuild snapshots: its value is the last one a
        # build actually validated.
        options_store = OptionsStore(self._persister)
        if not self._options_dirty:
            self._options_rollback = options_store.snapshot_raw()
            self._options_dirty = True
        options_store.store(merged)
        # stop only the event-loop thread; _run_locked sees the reload
        # flag, rebuilds over the same persister/agent, and swaps the
        # API server's scheduler — the process and socket survive
        self._reload_requested.set()
        if scheduler is not None:
            scheduler.stop()
        return 200, {
            "message": "update accepted; rolling update beginning",
            "env": sorted(env),
        }

    def _rollback_options(self) -> None:
        """Restore the options node to its pre-update value after a
        failed rebuild, so the next restart renders the last-good
        spec instead of re-failing on the poisoned overrides."""
        with self._update_lock:
            if not self._options_dirty:
                return
            if self._reload_requested.is_set():
                # another update was validated, persisted, and
                # acknowledged (HTTP 200) while this rebuild was
                # failing — its node value must survive; the restart
                # will render IT, not the poisoned intermediate
                LOG.warning(
                    "rebuild failed but a newer accepted update is "
                    "pending; leaving its options in place"
                )
                return
            prev = self._options_rollback
            try:
                OptionsStore(self._persister).restore_raw(prev)
                LOG.warning(
                    "rolled options back to pre-update value after "
                    "rebuild failure"
                )
                self._options_rollback = None
                self._options_dirty = False
            except Exception:
                LOG.exception("options rollback failed")

    def run(self) -> int:
        """Lock -> build -> serve -> loop.  Returns a process exit code."""
        if not self._lock.acquire():
            if self._stop_requested.is_set():
                # an HA standby asked to stop while candidating: a
                # clean exit, not a lock conflict — a supervisor must
                # not treat the operator's own stop as a crash
                LOG.info("stopped while standing by for the lease")
                return 0
            LOG.error(
                "another scheduler instance holds the lock for %s",
                self.config.state_dir,
            )
            return EXIT_LOCKED
        try:
            return self._run_locked()
        finally:
            self._lock.release()

    def _run_locked(self) -> int:
        from dcos_commons_tpu.http.server import ApiServer

        try:
            self.build()
        except Exception:
            LOG.exception("invalid configuration")
            return EXIT_BAD_CONFIG
        # API up before the loop starts taking work, so operators can
        # always observe (FrameworkRunner.java:130-138)
        extra_routes = self._make_extra_routes()
        self.api_server = ApiServer(
            self.scheduler,
            port=self.config.api_port,
            host=self.api_bind,
            extra_routes=extra_routes,
            auth_token=self.config.auth_token,
            tls=self.config.api_tls,
        ).start()
        thread = None
        try:
            self._set_artifact_base()
            if self.announce_file:
                from dcos_commons_tpu.common import atomic_write_text

                atomic_write_text(
                    self.announce_file, self.api_server.url + "\n"
                )
            LOG.info(
                "serving %s on %s (%d hosts, %s agents)",
                self.spec.name,
                self.api_server.url,
                len(self.topology_hosts),
                "remote" if self.agent_urls else "local",
            )
            lease = getattr(self._lock, "lease", None)
            if lease is not None:
                LOG.info(
                    "HA leader for %s at lease epoch %d "
                    "(failover state at %s/v1/debug/ha)",
                    self.spec.name, lease.epoch, self.api_server.url,
                )
            tracer = getattr(self.scheduler, "tracer", None)
            if tracer is not None and tracer.enabled:
                # the causal timeline operators join sandbox logs
                # against: GET /v1/debug/trace (text) or ?fmt=chrome
                # (Perfetto-loadable)
                LOG.info(
                    "flight recorder: %d spans at %s/v1/debug/trace",
                    tracer.capacity, self.api_server.url,
                )
            thread = self.scheduler.run_forever()
            try:
                while not self._stop_requested.is_set():
                    thread.join(timeout=0.5)
                    if self._reload_requested.is_set():
                        # live update: the loop (not the HTTP thread)
                        # owns the swap.  Checked EVERY iteration so an
                        # update landing at any moment — including just
                        # after a previous rebuild — is applied; stop
                        # is idempotent.  Rebuild over the SAME
                        # persister/agent/inventory; the process and
                        # its socket survive.
                        self.scheduler.stop()
                        thread.join(timeout=10)
                        self._reload_requested.clear()
                        try:
                            self.build()
                        except Exception:
                            LOG.exception("rebuild after update failed")
                            self._rollback_options()
                            return EXIT_BAD_CONFIG
                        # clear the rollback only when no further
                        # update is already pending — and under the
                        # update lock, so a concurrent handler's
                        # snapshot can't be clobbered
                        with self._update_lock:
                            if not self._reload_requested.is_set():
                                self._options_rollback = None
                                self._options_dirty = False
                        self._set_artifact_base()
                        self.api_server.set_scheduler(self.scheduler)
                        self.api_server.set_extra_routes(
                            self._make_extra_routes()
                        )
                        LOG.info("live update applied; scheduler rebuilt")
                        thread = self.scheduler.run_forever()
                        continue
                    if not thread.is_alive():
                        break  # loop died on its own (wedge etc.)
                    if self._uninstall_finished():
                        break
            except KeyboardInterrupt:
                pass
        finally:
            self.scheduler.stop()
            if thread is not None:
                thread.join(timeout=10)
            self.api_server.stop()
        if self._lease_lost:
            # another scheduler may already be active over this state
            LOG.critical("exiting after lease loss: %s", self._lease_lost)
            return EXIT_LOCKED
        fatal = getattr(self.scheduler, "fatal_error", None)
        if fatal:
            LOG.critical("scheduler wedged: %s", fatal)
            return EXIT_WEDGED
        return 0

    def _make_extra_routes(self) -> list:
        """Custom framework endpoints + the live-update route.  Rebuilt
        on every live update because routes_hook handlers close over
        the scheduler object."""
        extra = (
            list(self.routes_hook(self.scheduler))
            if self.routes_hook is not None else []
        )
        # live options update (reference: the Cosmos/CLI `update` flow)
        extra.append((
            "POST", r"/v1/update",
            lambda m, q, body: self.update_options(body.get("env")),
            True,
        ))
        return extra

    def _set_artifact_base(self) -> None:
        if hasattr(self.scheduler, "artifact_base") and self.agent_urls:
            # URL-mode template pulls are for REMOTE agents only: an
            # in-process agent fetching from this scheduler's own API
            # while the event loop holds its lock would deadlock —
            # local agents get template content inline
            self.scheduler.artifact_base = (
                self.advertise_url.rstrip("/") or self.api_server.url
            )

    def _uninstall_finished(self) -> bool:
        if not self.config.uninstall:
            return False
        is_complete = getattr(self.scheduler, "is_complete", None)
        if callable(is_complete):
            is_complete = is_complete()
        return bool(is_complete)

    def stop(self) -> None:
        self._stop_requested.set()
        abort = getattr(self._lock, "abort", None)
        if callable(abort):
            abort()  # release a candidate parked in acquire()
        if self.scheduler is not None:
            self.scheduler.stop()


class MultiFrameworkRunner:
    """One framework process hosting N services (reference:
    MultiServiceRunner + Multi*Resource routing).  Services are seeded
    from svc.yml args and managed dynamically over
    PUT/DELETE /v1/multi/<name>; the ServiceStore persists the set so
    restarts reload every service mid-plan."""

    def __init__(
        self,
        specs: List,
        config: Optional[SchedulerConfig] = None,
        topology_hosts: Optional[List[TpuHost]] = None,
        agent_urls: Optional[Dict[str, str]] = None,
        builder_hook=None,
    ):
        self.specs = list(specs)
        self.config = config or SchedulerConfig.from_env()
        self.topology_hosts = topology_hosts or []
        self.agent_urls = agent_urls or {}
        self.builder_hook = builder_hook
        self.multi = None
        self.api_server = None
        self.announce_file: str = ""
        self.api_bind: str = "127.0.0.1"
        self.advertise_url: str = ""
        self._stop_requested = threading.Event()
        self._lock = make_instance_lock(self.config, "multi-scheduler")
        self._lease_lost: Optional[str] = None
        # same CuratorLocker-style fatality as FrameworkRunner
        FrameworkRunner._wire_lease_loss(self)

    def build(self) -> None:
        from dcos_commons_tpu.multi import MultiServiceScheduler
        from dcos_commons_tpu.offer.inventory import SliceInventory

        inventory = SliceInventory(self.topology_hosts)
        if self.agent_urls:
            from dcos_commons_tpu.agent.remote import RemoteFleet

            fleet = RemoteFleet(
                on_host_down=inventory.mark_down,
                on_host_up=inventory.mark_up,
                auth_token=self.config.auth_token,
                ca_file=self.config.tls_ca_file,
            )
            for host_id, url in self.agent_urls.items():
                fleet.add_host(host_id, url)
            agent = fleet
        else:
            from dcos_commons_tpu.agent.local import LocalProcessAgent

            agent = LocalProcessAgent(self.config.sandbox_root)
        from dcos_commons_tpu.scheduler.builder import make_persister

        persister = make_persister(self.config)
        ha_state = None
        lease = getattr(self._lock, "lease", None)
        if lease is not None:
            from dcos_commons_tpu.ha.election import (
                FencedPersister,
                HAState,
            )

            persister = FencedPersister(persister, lease)
            ha_state = HAState(persister, lease.name, lease=lease)
        self.multi = MultiServiceScheduler(
            persister=persister,
            inventory=inventory,
            agent=agent,
            scheduler_config=self.config,
            builder_hook=(
                (lambda b: self.builder_hook(b, None))
                if self.builder_hook else None
            ),
            ha_state=ha_state,
        )
        for spec in self.specs:
            if self.multi.get_service(spec.name) is None:
                self.multi.add_service(spec)

    def run(self) -> int:
        if not self._lock.acquire():
            if self._stop_requested.is_set():
                # see FrameworkRunner.run: an aborted HA candidate is
                # a clean stop, not a lock conflict
                LOG.info("stopped while standing by for the lease")
                return 0
            LOG.error("another scheduler instance holds the lock")
            return EXIT_LOCKED
        try:
            return self._run_locked()
        finally:
            self._lock.release()

    def _run_locked(self) -> int:
        from dcos_commons_tpu.http.server import ApiServer

        try:
            self.build()
        except Exception:
            LOG.exception("invalid configuration")
            return EXIT_BAD_CONFIG
        self.api_server = ApiServer(
            port=self.config.api_port, host=self.api_bind, multi=self.multi,
            auth_token=self.config.auth_token, tls=self.config.api_tls,
        ).start()
        thread = None
        try:
            if self.agent_urls:
                # see FrameworkRunner: URL-mode templates only for
                # remote fleets; local agents take content inline
                self.multi.artifact_base = (
                    self.advertise_url.rstrip("/") or self.api_server.url
                )
            if self.announce_file:
                from dcos_commons_tpu.common import atomic_write_text

                atomic_write_text(
                    self.announce_file, self.api_server.url + "\n"
                )
            LOG.info(
                "serving %d services on %s (%d hosts)",
                len(self.multi.service_names()),
                self.api_server.url,
                len(self.topology_hosts),
            )
            thread = self.multi.run_forever()
            try:
                while thread.is_alive() and not self._stop_requested.is_set():
                    thread.join(timeout=0.5)
            except KeyboardInterrupt:
                pass
        finally:
            self.multi.stop()
            if thread is not None:
                thread.join(timeout=10)
            self.api_server.stop()
        if self._lease_lost:
            LOG.critical("exiting after lease loss: %s", self._lease_lost)
            return EXIT_LOCKED
        if getattr(self.multi, "fatal_error", None):
            LOG.critical("multi scheduler wedged: %s", self.multi.fatal_error)
            return EXIT_WEDGED
        return 0

    def stop(self) -> None:
        self._stop_requested.set()
        abort = getattr(self._lock, "abort", None)
        if callable(abort):
            abort()  # release a candidate parked in acquire()
        if self.multi is not None:
            self.multi.stop()


def serve_main(
    argv: Optional[List[str]] = None, builder_hook=None, routes_hook=None
) -> int:
    """``python -m dcos_commons_tpu serve`` argument handling."""
    import argparse

    from dcos_commons_tpu.specification.yaml_spec import from_yaml_file

    parser = argparse.ArgumentParser(
        prog="dcos_commons_tpu serve",
        description="Run a service scheduler process",
    )
    parser.add_argument(
        "svc_yml",
        nargs="*",
        help="service definition YAML(s); exactly one unless --multi",
    )
    parser.add_argument(
        "--topology", required=True, help="fleet topology YAML (hosts)"
    )
    parser.add_argument(
        "--multi",
        action="store_true",
        help="host MANY services in one framework process; services are "
             "seeded from svc_yml args and managed dynamically over "
             "PUT/DELETE /v1/multi/<name>",
    )
    parser.add_argument("--port", type=int, default=None, help="API port")
    parser.add_argument("--state-dir", default=None)
    parser.add_argument(
        "--state-url",
        default=None,
        help="cluster state server URL (remote persistence + lease "
             "lock; omit for local file WAL state)",
    )
    parser.add_argument(
        "--ha",
        action="store_true",
        help="HA leader election (requires --state-url): extra "
             "scheduler processes become hot standbys that take over "
             "on leader death; store writes are lease-epoch fenced "
             "(also $SDK_HA)",
    )
    parser.add_argument(
        "--secrets-dir",
        default=None,
        help="operator-managed secrets directory (FileSecretsProvider)",
    )
    parser.add_argument("--sandbox-root", default=None)
    parser.add_argument(
        "--trace-capacity",
        type=int,
        default=None,
        help="flight-recorder span capacity (0 disables tracing; "
             "also $TRACE_CAPACITY)",
    )
    parser.add_argument(
        "--env",
        action="append",
        default=[],
        metavar="KEY=VALUE",
        help="extra env for svc.yml template interpolation",
    )
    parser.add_argument(
        "--announce-file",
        default="",
        help="write the API URL here once listening (ephemeral ports)",
    )
    parser.add_argument(
        "--bind",
        default="127.0.0.1",
        help="API bind address (0.0.0.0 for multi-host fleets)",
    )
    parser.add_argument(
        "--advertise-url",
        default="",
        help="externally-reachable API URL handed to agents for "
             "artifact pulls (required when agents run on other hosts)",
    )
    parser.add_argument(
        "--auth-token-file",
        default="",
        help="cluster bearer token file — required on every control-"
             "plane request (API, agents, state server) when set; "
             "also $AUTH_TOKEN(_FILE)",
    )
    parser.add_argument("--tls-cert", default="",
                        help="serve the API over HTTPS: cert PEM")
    parser.add_argument("--tls-key", default="",
                        help="serve the API over HTTPS: key PEM")
    parser.add_argument(
        "--tls-ca", default="",
        help="CA bundle for verifying agent/state-server HTTPS; "
             "also $TLS_CA_FILE",
    )
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=os.environ.get("LOG_LEVEL", "INFO"),
        format="%(asctime)s %(levelname)s %(name)s %(message)s",
    )
    env = dict(os.environ)
    for pair in args.env:
        key, _, value = pair.partition("=")
        env[key] = value
    config = SchedulerConfig.from_env(env)
    if args.port is not None:
        config.api_port = args.port
    if args.state_dir is not None:
        config.state_dir = args.state_dir
    if args.state_url is not None:
        config.state_url = args.state_url
    if args.ha:
        config.ha_enabled = True
    if config.ha_enabled and not config.state_url:
        print(
            "configuration error: --ha requires --state-url (the "
            "leader lease lives in the replicated state tree)",
            file=sys.stderr,
        )
        return EXIT_BAD_CONFIG
    if args.secrets_dir is not None:
        config.secrets_dir = args.secrets_dir
    if args.sandbox_root is not None:
        config.sandbox_root = args.sandbox_root
    if args.trace_capacity is not None:
        config.trace_capacity = args.trace_capacity
    if args.auth_token_file:
        from dcos_commons_tpu.security.auth import load_token

        config.auth_token = load_token(token_file=args.auth_token_file)
    if args.tls_cert:
        config.tls_cert_file = args.tls_cert
    if args.tls_key:
        config.tls_key_file = args.tls_key
    if args.tls_ca:
        config.tls_ca_file = args.tls_ca
    try:
        config.api_tls  # half a cert/key pair is a config error
    except ValueError as e:
        print(f"configuration error: {e}", file=sys.stderr)
        return EXIT_BAD_CONFIG
    if not config.auth_token and args.bind not in (
        "127.0.0.1", "localhost", "::1"
    ):
        print(
            "WARNING: scheduler API bound on a non-loopback address with "
            "NO auth token — any reachable client can drive plans and "
            "kill tasks. Pass --auth-token-file "
            "(see security/auth.py trust model).",
            file=sys.stderr,
        )
    try:
        if not args.multi and len(args.svc_yml) != 1:
            raise ValueError(
                "exactly one svc.yml required (or pass --multi)"
            )
        specs = [from_yaml_file(path, env) for path in args.svc_yml]
        hosts, urls = load_topology(args.topology)
    except Exception as e:
        print(f"configuration error: {e}", file=sys.stderr)
        return EXIT_BAD_CONFIG
    if args.multi:
        if routes_hook is not None:
            # silent dropping would make a framework's discovery
            # endpoint vanish with no hint; refuse loudly
            print(
                "configuration error: custom routes (routes_hook) are "
                "not supported with --multi",
                file=sys.stderr,
            )
            return EXIT_BAD_CONFIG
        runner = MultiFrameworkRunner(
            specs, config, topology_hosts=hosts, agent_urls=urls,
            builder_hook=builder_hook,
        )
    else:
        runner = FrameworkRunner(
            specs[0], config, topology_hosts=hosts, agent_urls=urls,
            builder_hook=builder_hook,
            spec_source=(args.svc_yml[0], env),
        )
        runner.routes_hook = routes_hook
    runner.announce_file = args.announce_file
    runner.api_bind = args.bind
    runner.advertise_url = args.advertise_url

    def _sigterm(signum, frame):
        runner.stop()

    signal.signal(signal.SIGTERM, _sigterm)
    signal.signal(signal.SIGINT, _sigterm)
    return runner.run()
