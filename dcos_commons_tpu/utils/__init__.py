"""Workload utilities: data, checkpointing, tree math."""

from dcos_commons_tpu.utils.data import synthetic_tokens, synthetic_mnist
from dcos_commons_tpu.utils.tree import param_count, param_bytes
from dcos_commons_tpu.utils.checkpoint import (
    AsyncCheckpointer,
    StaleWriterError,
    claim_incarnation,
    restore_checkpoint,
    save_checkpoint,
)
from dcos_commons_tpu.utils.compile_cache import enable_compilation_cache
from dcos_commons_tpu.utils.microbatch import (
    MicroBatcher,
    WorkItem,
    pack_mixed_rows,
    unpack_results,
)

__all__ = [
    "AsyncCheckpointer",
    "MicroBatcher",
    "StaleWriterError",
    "WorkItem",
    "claim_incarnation",
    "enable_compilation_cache",
    "pack_mixed_rows",
    "unpack_results",
    "param_bytes",
    "param_count",
    "restore_checkpoint",
    "save_checkpoint",
    "synthetic_mnist",
    "synthetic_tokens",
]
