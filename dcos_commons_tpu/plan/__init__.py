"""L2 plan engine: Plan -> Phase -> Step state machines + strategies.

Reference: sdk/scheduler/.../scheduler/plan/ — Element.java:18,
Plan.java:23, Phase.java:12, Step.java:15, Status.java:23-78,
DefaultPlanCoordinator.java:33-90, PlanScheduler.java:50-100,
DeploymentStep.java:122-193, strategy/ (SerialStrategy,
ParallelStrategy, CanaryStrategy.java:30-58, DependencyStrategy,
RandomStrategy), backoff/ExponentialBackoff.java:30-50.
"""

from dcos_commons_tpu.plan.status import Status
from dcos_commons_tpu.plan.element import Element
from dcos_commons_tpu.plan.step import DeploymentStep, PodInstanceRequirement, RecoveryType, Step
from dcos_commons_tpu.plan.phase import Phase
from dcos_commons_tpu.plan.plan import Plan
from dcos_commons_tpu.plan.strategy import (
    CanaryStrategy,
    DependencyStrategy,
    ParallelStrategy,
    RandomStrategy,
    SerialStrategy,
    Strategy,
    strategy_for_name,
)
from dcos_commons_tpu.plan.backoff import Backoff, DisabledBackoff, ExponentialBackoff
from dcos_commons_tpu.plan.plan_manager import DefaultPlanManager, PlanManager
from dcos_commons_tpu.plan.coordinator import DefaultPlanCoordinator
from dcos_commons_tpu.plan.builders import DeployPlanFactory
from dcos_commons_tpu.plan.generator import PlanGenerator

__all__ = [
    "Backoff",
    "CanaryStrategy",
    "DefaultPlanCoordinator",
    "DefaultPlanManager",
    "DependencyStrategy",
    "DeployPlanFactory",
    "DeploymentStep",
    "DisabledBackoff",
    "Element",
    "ExponentialBackoff",
    "ParallelStrategy",
    "Phase",
    "Plan",
    "PlanGenerator",
    "PlanManager",
    "PodInstanceRequirement",
    "RandomStrategy",
    "RecoveryType",
    "SerialStrategy",
    "Status",
    "Step",
    "Strategy",
    "strategy_for_name",
]
