"""RemoteFleet: the scheduler's view of a fleet of per-host agents.

Implements the Agent contract over HTTP against N AgentDaemon
processes (one per TPU host), making the control plane distributed in
fact: launches route to the daemon owning the task's placed host,
statuses are pulled over real sockets, and an unreachable daemon is
detected and surfaced as host-down + TASK_LOST so the recovery
machinery replaces its tasks — the role Mesos master partition
signals play for the reference (FrameworkRunner.java:185-189
PARTITION_AWARE; agent loss -> TASK_LOST fan-in).
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional, Set, Tuple

from dcos_commons_tpu.agent.base import Agent
from dcos_commons_tpu.agent.daemon import serialize_check
from dcos_commons_tpu.common import TaskInfo, TaskState, TaskStatus

LOG = logging.getLogger(__name__)


class RemoteAgentClient:
    """HTTP client for one host's AgentDaemon."""

    def __init__(
        self,
        host_id: str,
        base_url: str,
        timeout_s: float = 5.0,
        launch_timeout_s: float = 30.0,
        auth_token: str = "",
        ca_file: str = "",
    ):
        from dcos_commons_tpu.security import auth as _auth

        self.host_id = host_id
        self.base_url = base_url.rstrip("/")
        self.timeout_s = timeout_s
        # launches block on daemon-side template fetches (10s per
        # template); a timeout shorter than that would declare a
        # successfully-launching task LOST and double-book the slice
        self.launch_timeout_s = launch_timeout_s
        self._headers = {"Content-Type": "application/json",
                         **_auth.auth_headers(auth_token)}
        self._ssl_ctx = (
            _auth.client_ssl_context(ca_file)
            if self.base_url.startswith("https") else None
        )

    def _request(
        self,
        method: str,
        path: str,
        body: Optional[dict] = None,
        timeout_s: Optional[float] = None,
    ):
        data = json.dumps(body).encode("utf-8") if body is not None else None
        req = urllib.request.Request(
            f"{self.base_url}{path}",
            data=data,
            method=method,
            headers=dict(self._headers),
        )
        with urllib.request.urlopen(
            req,
            timeout=timeout_s if timeout_s is not None else self.timeout_s,
            context=self._ssl_ctx,
        ) as resp:
            return json.loads(resp.read().decode("utf-8"))

    def info(self) -> dict:
        return self._request("GET", "/v1/agent/info")

    def launch(self, entries: List[dict]) -> List[str]:
        # each config template may cost the daemon a fetch of up to 10s
        # (agent/local.py prepare_templates); size the RPC timeout to
        # the request or a false timeout here double-books the task
        n_templates = sum(len(e.get("templates") or []) for e in entries)
        # artifact downloads can be big (corpus/tokenizer staging);
        # digest-cached relaunches return fast but the first fetch
        # must not be declared dead mid-download
        n_uris = sum(len(e.get("uris") or []) for e in entries)
        return self._request(
            "POST",
            "/v1/agent/launch",
            {"tasks": entries},
            timeout_s=self.launch_timeout_s + 12.0 * n_templates
            + 130.0 * n_uris,
        )["launched"]

    def kill(self, task_id: str, grace_period_s: float) -> None:
        self._request(
            "POST",
            "/v1/agent/kill",
            {"task_id": task_id, "grace_period_s": grace_period_s},
        )

    def tasks(self) -> Set[str]:
        return set(self._request("GET", "/v1/agent/tasks")["task_ids"])

    def reconcile(self) -> None:
        self._request("POST", "/v1/agent/reconcile")

    def drain(self) -> List[TaskStatus]:
        raw = self._request("POST", "/v1/agent/drain")
        return [TaskStatus.from_dict(s) for s in raw["statuses"]]

    def steplog_of(self, task_name: str) -> List[dict]:
        """Worker step telemetry off the daemon's sandbox (the remote
        half of LocalProcessAgent.steplog_of)."""
        from urllib.parse import quote

        body = self._request(
            "GET", f"/v1/agent/steplog?task={quote(task_name)}"
        )
        records = body.get("records")
        return records if isinstance(records, list) else []

    def serving_stats_of(self, task_name: str) -> dict:
        """Serving-engine gauges off the daemon's sandbox."""
        from urllib.parse import quote

        body = self._request(
            "GET", f"/v1/agent/servestats?task={quote(task_name)}"
        )
        stats = body.get("stats")
        return stats if isinstance(stats, dict) else {}

    def sandbox_file(self, task_name: str, rel: str = "stdout") -> str:
        from urllib.parse import quote

        req = urllib.request.Request(
            f"{self.base_url}/v1/agent/sandbox"
            f"?task={quote(task_name)}&file={quote(rel)}",
            headers=dict(self._headers),
        )
        with urllib.request.urlopen(
            req, timeout=self.timeout_s, context=self._ssl_ctx
        ) as resp:
            return resp.read().decode("utf-8")


class RemoteFleet(Agent):
    """Agent multiplexer over per-host daemons, keyed by ``agent_id``.

    Host-down detection: ``down_after`` consecutive failed polls of a
    daemon declare its host down — tracked tasks on it get synthesized
    TASK_LOST and ``on_host_down(host_id)`` fires (the runner wires it
    to SliceInventory.mark_down so placement stops offering the host).
    A successful poll afterwards fires ``on_host_up``.
    """

    is_remote = True

    def __init__(
        self,
        timeout_s: float = 5.0,
        down_after: int = 3,
        on_host_down: Optional[Callable[[str], None]] = None,
        on_host_up: Optional[Callable[[str], None]] = None,
        auth_token: str = "",
        ca_file: str = "",
    ):
        self._clients: Dict[str, RemoteAgentClient] = {}
        self._timeout_s = timeout_s
        self._auth_token = auth_token
        self._ca_file = ca_file
        self._down_after = down_after
        self._failures: Dict[str, int] = {}
        self._down: Set[str] = set()
        # task_id -> host_id for kill routing + LOST synthesis; rebuilt
        # lazily from daemon task lists after a scheduler restart
        self._owners: Dict[str, str] = {}
        # telemetry routes by task NAME: a generation-stamped lazy
        # index over _owners (every mutation bumps _owners_gen, the
        # index rebuilds once per change) — the health monitor makes
        # TWO name lookups per task per refresh, and a linear
        # owner-map scan per lookup would be O(tasks^2) per refresh
        # under the fleet lock
        self._owners_gen = 0
        self._owner_names: Dict[str, str] = {}
        self._owner_names_gen = -1
        self._pending: List[TaskStatus] = []
        self.on_host_down = on_host_down
        self.on_host_up = on_host_up
        self._lock = threading.RLock()
        # per-host RPCs fan out concurrently so one unreachable host's
        # connect timeout cannot stall the whole scheduler cycle
        self._pool: Optional[ThreadPoolExecutor] = None

    def _fan_out(self, fn) -> List[Tuple[str, object]]:
        """Run ``fn(host_id, client)`` for every host concurrently;
        returns [(host_id, result-or-exception)] in host order."""
        with self._lock:
            clients = sorted(self._clients.items())
            if self._pool is None or self._pool._max_workers < len(clients):
                if self._pool is not None:
                    self._pool.shutdown(wait=False)
                self._pool = ThreadPoolExecutor(
                    max_workers=max(4, len(clients)),
                    thread_name_prefix="fleet-rpc",
                )
            pool = self._pool

        def call(item):
            host_id, client = item
            try:
                return host_id, fn(host_id, client)
            except Exception as e:  # scored by the caller
                return host_id, e

        return list(pool.map(call, clients))

    def add_host(self, host_id: str, url: str) -> None:
        with self._lock:
            self._clients[host_id] = RemoteAgentClient(
                host_id, url, self._timeout_s,
                auth_token=self._auth_token, ca_file=self._ca_file,
            )
            self._failures[host_id] = 0

    def hosts(self) -> List[str]:
        with self._lock:
            return sorted(self._clients)

    def client(self, host_id: str) -> Optional[RemoteAgentClient]:
        return self._clients.get(host_id)

    # -- Agent --------------------------------------------------------

    def launch(self, task_infos: List[TaskInfo]) -> None:
        for info in task_infos:
            self.launch_one(info)

    def launch_one(
        self,
        info: TaskInfo,
        readiness=None,
        health=None,
        templates: Optional[List[dict]] = None,
        files: Optional[List[dict]] = None,
        secret_env: Optional[Dict[str, str]] = None,
        kill_grace_s: float = 5.0,
        uris: Optional[List[dict]] = None,
        rlimits: Optional[List[dict]] = None,
    ) -> None:
        client = self._clients.get(info.agent_id)
        if client is None:
            self._fail_launch(info, f"no agent for host {info.agent_id!r}")
            return
        entry = {
            "info": info.to_dict(),
            "readiness": serialize_check(readiness),
            "health": serialize_check(health),
            "templates": templates or [],
            "files": files or [],
            "secret_env": secret_env or {},
            "kill_grace_s": kill_grace_s,
            "uris": uris or [],
            "rlimits": rlimits or [],
        }
        try:
            client.launch([entry])
        except (urllib.error.URLError, OSError, json.JSONDecodeError) as e:
            # the daemon may be mid-crash: surface LOST so recovery
            # replaces the task instead of the step hanging in STARTING
            self._fail_launch(info, f"agent unreachable at launch: {e}")
            return
        with self._lock:
            if self._owners.get(info.task_id) != info.agent_id:
                self._owners[info.task_id] = info.agent_id
                self._owners_gen += 1

    def _fail_launch(self, info: TaskInfo, message: str) -> None:
        LOG.warning("launch of %s failed: %s", info.task_id, message)
        with self._lock:
            self._pending.append(
                TaskStatus(
                    task_id=info.task_id,
                    state=TaskState.LOST,
                    message=message,
                    agent_id=info.agent_id,
                )
            )

    def kill(self, task_id: str, grace_period_s: float = 0.0) -> None:
        with self._lock:
            owner = self._owners.get(task_id)
        if owner and owner in self._clients:
            try:
                self._clients[owner].kill(task_id, grace_period_s)
            except (urllib.error.URLError, OSError):
                pass  # TaskKiller retries until a terminal status lands
            return
        # unknown owner (restart before any poll): broadcast — kill of
        # an unknown id is an idempotent no-op daemon-side
        self._fan_out(lambda _h, c: c.kill(task_id, grace_period_s))

    def active_task_ids(self) -> Set[str]:
        out: Set[str] = set()
        for host_id, result in self._fan_out(lambda _h, c: c.tasks()):
            if isinstance(result, Exception):
                # liveness is only scored by poll() — a scheduler cycle
                # calls both methods, and double-counting would halve
                # the documented down_after threshold.  A down host's
                # tasks count as active until LOST is synthesized by
                # poll(), so the reconciler doesn't double-report them.
                with self._lock:
                    out |= {
                        t for t, h in self._owners.items() if h == host_id
                    }
                continue
            self._note_success(host_id)
            with self._lock:
                for task_id in result:
                    if task_id not in self._owners:
                        self._owners[task_id] = host_id
                        self._owners_gen += 1
            out |= result
        return out

    def reconcile(self) -> None:
        """Explicit reconciliation across the fleet (the Reconciler's
        startup hook): every reachable daemon re-arms its tasks'
        CURRENT states for the next drain, so statuses a dead
        scheduler drained but never acted on are re-delivered to its
        successor.  Best-effort per host — an unreachable daemon's
        tasks are handled by poll()'s down-host LOST synthesis."""
        for host_id, result in self._fan_out(
            lambda _h, c: c.reconcile()
        ):
            if isinstance(result, Exception):
                LOG.info("reconcile skipped on %s: %s", host_id, result)

    def poll(self) -> List[TaskStatus]:
        out: List[TaskStatus] = []
        with self._lock:
            out.extend(self._pending)
            self._pending.clear()
        for host_id, statuses in self._fan_out(lambda _h, c: c.drain()):
            if isinstance(statuses, Exception):
                self._note_failure(host_id)
                # the threshold may have been crossed by a failed
                # active_task_ids() call between polls; LOST synthesis
                # is idempotent (owners entries are consumed), so run
                # it whenever the host is down
                with self._lock:
                    is_down = host_id in self._down
                if is_down:
                    out.extend(self._lose_tasks_on(host_id))
                continue
            self._note_success(host_id)
            for status in statuses:
                with self._lock:
                    # bump the generation only when the map actually
                    # changed: a reconcile()-re-emitted RUNNING is a
                    # no-op here, and a spurious bump would rebuild
                    # the telemetry name index every refresh
                    if status.state.is_terminal:
                        if self._owners.pop(status.task_id, None) is not None:
                            self._owners_gen += 1
                    elif status.task_id not in self._owners:
                        self._owners[status.task_id] = host_id
                        self._owners_gen += 1
                out.append(status)
        return out

    # -- host liveness ------------------------------------------------

    def _note_failure(self, host_id: str) -> bool:
        """Returns True when this failure crosses the down threshold."""
        with self._lock:
            self._failures[host_id] = self._failures.get(host_id, 0) + 1
            if (
                self._failures[host_id] >= self._down_after
                and host_id not in self._down
            ):
                self._down.add(host_id)
                LOG.warning(
                    "agent %s unreachable %d times: declaring host down",
                    host_id, self._failures[host_id],
                )
                callback = self.on_host_down
            else:
                return False
        if callback is not None:
            callback(host_id)
        return True

    def _note_success(self, host_id: str) -> None:
        with self._lock:
            self._failures[host_id] = 0
            if host_id not in self._down:
                return
            self._down.discard(host_id)
            callback = self.on_host_up
        LOG.info("agent %s reachable again: host back up", host_id)
        if callback is not None:
            callback(host_id)

    def _lose_tasks_on(self, host_id: str) -> List[TaskStatus]:
        with self._lock:
            lost = [t for t, h in self._owners.items() if h == host_id]
            for task_id in lost:
                del self._owners[task_id]
            if lost:
                self._owners_gen += 1
        return [
            TaskStatus(
                task_id=task_id,
                state=TaskState.LOST,
                message=f"host {host_id} unreachable",
                agent_id=host_id,
            )
            for task_id in lost
        ]

    def down_hosts(self) -> Set[str]:
        with self._lock:
            return set(self._down)

    # -- worker telemetry fan-in (best-effort) ------------------------

    def _owner_client(self, task_name: str) -> Optional[RemoteAgentClient]:
        """The daemon holding ``task_name``'s sandbox, via the
        name-keyed owner index (rebuilt from the owner map only when
        it changed — so a telemetry refresh over N tasks costs O(N)
        once, not O(N^2); the owner map itself is rebuilt from daemon
        task lists after a restart, so a freshly failed-over scheduler
        regains telemetry after its first poll)."""
        from dcos_commons_tpu.common import task_name_of

        with self._lock:
            if self._owner_names_gen != self._owners_gen:
                names: Dict[str, str] = {}
                for task_id, host_id in self._owners.items():
                    try:
                        names[task_name_of(task_id)] = host_id
                    except ValueError:
                        continue
                self._owner_names = names
                self._owner_names_gen = self._owners_gen
            host_id = self._owner_names.get(task_name)
            if host_id is None or host_id in self._down:
                return None
            return self._clients.get(host_id)

    def _telemetry_client(
        self, task_name: str, agent_id: Optional[str]
    ) -> Optional[RemoteAgentClient]:
        """Callers that know which host owns the task (the health
        monitor reads ``info.agent_id`` from its own state store) pass
        it and route EXACTLY — task names are not service-qualified,
        so on a fleet shared by several services the name index could
        hand service A another service's same-named task.  Name-based
        lookup stays as the fallback for host-agnostic callers."""
        if agent_id:
            with self._lock:
                if agent_id in self._down:
                    return None
                return self._clients.get(agent_id)
        return self._owner_client(task_name)

    def steplog_of(
        self, task_name: str, agent_id: Optional[str] = None
    ) -> List[dict]:
        """Worker step telemetry over the wire — the production
        topology's half of the /v1/debug/trace merge and the
        straggler detector's input.  Best-effort by contract: no
        owner, a down host, or a failed RPC reads as "no telemetry",
        never as an error (liveness is poll()'s job — a telemetry
        probe must not move the down-detection counters)."""
        client = self._telemetry_client(task_name, agent_id)
        if client is None:
            return []
        try:
            return client.steplog_of(task_name)
        except (urllib.error.URLError, OSError, json.JSONDecodeError,
                ValueError):
            return []

    def serving_stats_of(
        self, task_name: str, agent_id: Optional[str] = None
    ) -> dict:
        """Serving-engine gauges over the wire (same best-effort
        contract as steplog_of)."""
        client = self._telemetry_client(task_name, agent_id)
        if client is None:
            return {}
        try:
            return client.serving_stats_of(task_name)
        except (urllib.error.URLError, OSError, json.JSONDecodeError,
                ValueError):
            return {}
