"""Bounded soak: the live event loop under continuous churn.

Reference: the scale/soak tier (SURVEY §4.4) — helloworld's scale test
plus long-running stability. Here: a real run_forever loop with a
churn injector killing tasks for ~15 s of wall clock, then invariants:
the loop never wedged, every failure recovered, per-instance recovery
state never ballooned DURING the churn, and the ledger still
reconciles with the store.
"""

import threading
import time

from dcos_commons_tpu.common import TaskState, TaskStatus
from dcos_commons_tpu.testing import (
    AdvanceCycles,
    ExpectDeploymentComplete,
    SendTaskRunning,
    ServiceTestRunner,
)

SOAK_YAML = """
name: soak
pods:
  app:
    count: 4
    tasks:
      main:
        goal: RUNNING
        cmd: "serve"
        cpus: 0.1
        memory: 32
"""

SOAK_SECONDS = 15.0


def test_event_loop_survives_churn():
    runner = ServiceTestRunner(SOAK_YAML)
    runner.run([AdvanceCycles(1)])
    for i in range(4):
        runner.run([SendTaskRunning(f"app-{i}-main")])
    runner.run([ExpectDeploymentComplete()])
    scheduler = runner.world.scheduler
    agent = runner.world.agent

    stop = threading.Event()
    churn_counts = {"kills": 0, "acks": 0}
    churn_errors = []

    def churn():
        """Fail tasks round-robin, then ack whatever relaunched."""
        try:
            i = 0
            while not stop.is_set():
                victim = f"app-{i % 4}-main"
                task_id = agent.task_id_of(victim)
                if task_id is not None and \
                        task_id in agent.active_task_ids():
                    agent.send(TaskStatus(
                        task_id=task_id, state=TaskState.FAILED,
                        message="churn",
                    ))
                    churn_counts["kills"] += 1
                time.sleep(0.05)
                # ack every staged relaunch so recovery keeps completing
                for info in list(agent.launched):
                    if info.task_id in agent.active_task_ids():
                        agent.send(TaskStatus(
                            task_id=info.task_id,
                            state=TaskState.RUNNING, ready=True,
                        ))
                        churn_counts["acks"] += 1
                i += 1
                time.sleep(0.05)
        except Exception as e:  # surfaced after join — a dead churner
            churn_errors.append(e)  # must fail the soak, not shorten it

    thread = scheduler.run_forever(interval_s=0.02)
    churner = threading.Thread(target=churn, daemon=True)
    churner.start()
    # sample recovery-phase accumulation WHILE churn runs: after the
    # quiesce the pruned plan is trivially small, so a live leak is
    # only observable here
    max_phases = 0
    deadline = time.monotonic() + SOAK_SECONDS
    while time.monotonic() < deadline:
        max_phases = max(
            max_phases, len(scheduler.plan("recovery").phases)
        )
        time.sleep(0.1)
    stop.set()
    churner.join(timeout=5)
    assert not churner.is_alive(), "churn thread failed to stop"
    assert not churn_errors, churn_errors

    # quiesce: keep acking until the agent queue is drained AND
    # recovery is complete (a FAILED still in flight would synthesize
    # a new phase right after an early exit)
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        for info in list(agent.launched):
            if info.task_id in agent.active_task_ids():
                agent.send(TaskStatus(
                    task_id=info.task_id,
                    state=TaskState.RUNNING, ready=True,
                ))
        queue_empty = not agent._queue
        if queue_empty and scheduler.plan("recovery").is_complete:
            break
        time.sleep(0.1)
    scheduler.stop()
    thread.join(timeout=10)
    assert not thread.is_alive(), "scheduler loop failed to stop"

    assert churn_counts["kills"] > 20, "churn never ran"
    assert churn_counts["acks"] > 0
    assert scheduler.fatal_error is None
    # re-check AFTER the loop stopped: nothing raced in behind the
    # quiesce observation
    assert scheduler.plan("recovery").is_complete
    # at most one live recovery phase per pod instance at any sampled
    # moment during churn (a fast recovery may complete between
    # samples, so no lower bound)
    assert max_phases <= 4, max_phases
    # every instance is RUNNING again
    statuses = scheduler.state_store.fetch_statuses()
    for i in range(4):
        assert statuses[f"app-{i}-main"].state is TaskState.RUNNING
    # ledger <-> store reconciliation: every live task's reservations
    # exist, and no reservation is orphaned
    owned = {info.name for info in scheduler.state_store.fetch_tasks()}
    for reservation in scheduler.ledger.all():
        assert reservation.task_name in owned
    for name in owned:
        assert scheduler.ledger.for_task(name)
