"""Launch backoff for crash-looping pods.

Reference: scheduler/plan/backoff/ — ExponentialBackoff.java:30-50
(initial * factor^attempts, capped at max; cleared on success) and
DisabledBackoff.java.  A backed-off step reads DELAYED
(DeploymentStep.java:176-182).
"""

from __future__ import annotations

import threading
from typing import Dict


class Backoff:
    def next_delay(self, key: str) -> float:
        """Record a failure for ``key``; return seconds to delay."""
        raise NotImplementedError

    def clear(self, key: str) -> None:
        raise NotImplementedError

    def current_delay(self, key: str) -> float:
        raise NotImplementedError


class DisabledBackoff(Backoff):
    def next_delay(self, key: str) -> float:
        return 0.0

    def clear(self, key: str) -> None:
        pass

    def current_delay(self, key: str) -> float:
        return 0.0


class ExponentialBackoff(Backoff):
    def __init__(
        self,
        initial_s: float = 1.0,
        factor: float = 1.15,
        max_s: float = 300.0,
    ):
        if initial_s <= 0 or factor < 1.0 or max_s < initial_s:
            raise ValueError("bad backoff parameters")
        self._initial = initial_s
        self._factor = factor
        self._max = max_s
        self._attempts: Dict[str, int] = {}
        self._lock = threading.Lock()

    def next_delay(self, key: str) -> float:
        with self._lock:
            attempts = self._attempts.get(key, 0)
            self._attempts[key] = attempts + 1
            return min(self._initial * (self._factor ** attempts), self._max)

    def clear(self, key: str) -> None:
        with self._lock:
            self._attempts.pop(key, None)

    def current_delay(self, key: str) -> float:
        with self._lock:
            attempts = self._attempts.get(key, 0)
            if attempts == 0:
                return 0.0
            return min(self._initial * (self._factor ** (attempts - 1)), self._max)
