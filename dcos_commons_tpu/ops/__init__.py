"""Pallas TPU kernels for the hot ops, with jnp fallbacks.

The reference links external native compute (libmesos JNI); our
native compute layer is XLA + these Pallas kernels (SURVEY.md section
2.2 native inventory note).  Every kernel has a jnp reference
implementation used as the CPU fallback and the correctness oracle;
kernels themselves are additionally testable on CPU via
``interpret=True``.
"""

from dcos_commons_tpu.ops.attention import flash_attention
from dcos_commons_tpu.ops.rmsnorm import rms_norm

__all__ = ["flash_attention", "rms_norm"]
