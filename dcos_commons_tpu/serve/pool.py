"""Device half of the continuous-batching engine: the jitted
prefill-into-slot / decode-step pair over a persistent slot-pool KV
cache (models/decode.py).

TWO compiles cover the server's whole life: ``prefill`` admits one
right-padded prompt (traced true_len/slot/temperature/seed — no
recompile per request) into a pool row, ``decode`` advances EVERY
row one step with per-row positions, temperatures and PRNG seeds (a
mixed greedy/sampling pool shares one dispatch).  The cache is
allocated ONCE at ``slots x max_len`` with static shapes and threaded
through both functions; on non-CPU backends the cache argument is
DONATED so XLA updates it in place instead of holding two pool-sized
buffers live across the call.

Per-row sampling keys: each request carries its own 31-bit seed and
every step folds the row's current position into it
(``fold_in(key(seed), pos)``) — rows never share randomness, a row's
stream does not depend on which slot it landed in or who its pool
neighbors are, and no key is ever reused across steps (the prefill
pick folds ``true_len - 1``, the first decode folds ``true_len``).
Greedy rows (temperature 0) ignore the keys entirely and argmax —
token-identical to whole-batch ``generate`` on the same prompts
(tests/test_continuous_batching.py holds the equivalence under
arbitrary admission orders).

The gang driver reuses this class unchanged: ``put`` lifts host
arrays to global (broadcast_one_to_all hands every rank identical
numpy), ``constrain_out`` pins token outputs replicated so rank 0
can bulk-fetch them, and ``cache_sharding`` lays the pool's KV heads
over the tp axis when divisible.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import numpy as np


class PoolModel:
    """Owns the slot-pool cache and the two compiled entry points.

    Not thread-safe by itself: exactly one thread (the engine loop, or
    a gang rank's tick executor) may call ``prefill``/``decode`` —
    both advance ``self.cache``.
    """

    def __init__(
        self,
        config,
        params,
        slots: int,
        max_len: int,
        kv_dtype: str = "native",
        cache_sharding: Optional[Any] = None,
        put: Optional[Callable] = None,
        constrain_out: Optional[Callable] = None,
    ):
        import jax
        import jax.numpy as jnp

        from dcos_commons_tpu.models.decode import (
            decode_step,
            init_kv_cache,
            prefill_into_slot,
            sample_token,
        )

        self._jax = jax
        self._np = np
        self.config = config
        self.params = params
        self.slots = slots
        self.max_len = max_len
        self._put = put if put is not None else (lambda x: x)
        con = constrain_out if constrain_out is not None else (lambda x: x)

        init = functools.partial(
            init_kv_cache, config, slots, max_len, kv_dtype
        )
        if cache_sharding is not None:
            self.cache = jax.jit(init, out_shardings=cache_sharding)()
        else:
            self.cache = jax.jit(init)()

        def _prefill(params, cache, tokens, slot, true_len, temp, seed):
            logits, cache = prefill_into_slot(
                config, params, cache, tokens, slot, true_len
            )
            key = jax.random.fold_in(jax.random.key(seed), true_len - 1)
            return con(sample_token(logits[0], temp, key)), cache

        def _decode(params, cache, tok, pos, temps, seeds):
            logits, cache = decode_step(config, params, cache, tok, pos)

            def pick_row(lg, temp, seed, p):
                key = jax.random.fold_in(jax.random.key(seed), p)
                return sample_token(lg, temp, key)

            nxt = jax.vmap(pick_row)(logits, temps, seeds, pos)
            return con(nxt), cache

        # donate the pool cache (argnums 1): decode streams it every
        # step — holding input AND output pools live would double the
        # dominant HBM term.  CPU has no donation; skip the warning.
        donate = {}
        if jax.default_backend() != "cpu":
            donate = {"donate_argnums": (1,)}
        self._prefill_c = jax.jit(_prefill, **donate)
        self._decode_c = jax.jit(_decode, **donate)
        self._jnp = jnp

    def prefill(
        self, tokens: np.ndarray, slot: int, true_len: int,
        temp: float, seed: int,
    ) -> int:
        """Admit one right-padded [1, prompt_len] prompt into pool row
        ``slot``; returns the first generated token."""
        first, self.cache = self._prefill_c(
            self.params, self.cache,
            self._put(np.asarray(tokens, np.int32)),
            np.int32(slot), np.int32(true_len),
            np.float32(temp), np.int32(seed),
        )
        return int(self._jax.device_get(first))

    def decode(
        self, tok: np.ndarray, pos: np.ndarray,
        temps: np.ndarray, seeds: np.ndarray,
        n_active: Optional[int] = None,
    ) -> np.ndarray:
        """One decode step over the WHOLE pool; returns next tokens
        [slots] (inactive rows' outputs are discarded by the engine).
        ``n_active`` is the engine's bookkeeping rider (the gang
        driver stamps it into the broadcast head); the computation
        always covers every slot — static shapes.  ONE bulk device
        fetch — per-element reads are a transfer each."""
        nxt, self.cache = self._decode_c(
            self.params, self.cache,
            self._put(np.asarray(tok, np.int32)),
            self._put(np.asarray(pos, np.int32)),
            self._put(np.asarray(temps, np.float32)),
            self._put(np.asarray(seeds, np.int32)),
        )
        return np.asarray(self._jax.device_get(nxt))

    def warm(self, prompt_len: int) -> None:
        """Compile + execute both entry points before readiness: the
        first request must not pay the compile, and a rank that cannot
        compile must fail deploy, not the first client."""
        self.prefill(
            np.zeros((1, prompt_len), np.int32),
            slot=0, true_len=prompt_len, temp=0.0, seed=0,
        )
        out = self.decode(
            np.zeros(self.slots, np.int32),
            np.full(self.slots, prompt_len, np.int32),
            np.zeros(self.slots, np.float32),
            np.zeros(self.slots, np.int32),
        )
        self._jax.block_until_ready(out)


class PagedPoolModel:
    """Device half of the PAGED engine: the jitted prefill-chunk /
    decode-step pair over a persistent page arena (models/decode.py
    ``init_paged_kv_cache`` / ``paged_prefill_chunk`` /
    ``paged_decode_step``).

    The two-compiles-per-lifetime property carries over from the slot
    pool: ONE prefill-chunk program (chunk width ``chunk_tokens``
    static; start position, true length, page table, temperature and
    seed all traced — a request resuming after a prefix-cache hit is
    the same program as one starting cold) and ONE decode program
    (per-row positions/temps/seeds/page tables traced) cover every
    request the server ever admits.  The arena holds ``pages`` usable
    pages plus the TRASH page (physical page 0): padding and
    inactive-row writes land there, so ``warm()`` — which runs both
    programs over all-zero tables — never dirties a real page.

    Not thread-safe by itself (the engine loop or a gang rank's tick
    executor is the single caller); the gang driver reuses it via the
    same ``put``/``constrain_out``/``cache_sharding`` riders as
    ``PoolModel`` — kv heads sit on dim 3 of the arena, exactly where
    the slot pool carried the tp axis.
    """

    def __init__(
        self,
        config,
        params,
        slots: int,
        max_len: int,
        page_tokens: int,
        pages: int,
        chunk_tokens: int,
        kv_dtype: str = "native",
        cache_sharding: Optional[Any] = None,
        put: Optional[Callable] = None,
        constrain_out: Optional[Callable] = None,
    ):
        import jax
        import jax.numpy as jnp

        from dcos_commons_tpu.models.decode import (
            init_paged_kv_cache,
            paged_decode_step,
            paged_prefill_chunk,
            sample_token,
        )
        from dcos_commons_tpu.serve.paging import pages_for

        self._jax = jax
        self.config = config
        self.params = params
        self.slots = slots
        self.max_len = max_len
        self.page_tokens = page_tokens
        self.pages = pages
        self.chunk_tokens = chunk_tokens
        self.pages_per_row = pages_for(max_len, page_tokens)
        self._put = put if put is not None else (lambda x: x)
        con = constrain_out if constrain_out is not None else (lambda x: x)

        init = functools.partial(
            init_paged_kv_cache, config, pages + 1, page_tokens,
            kv_dtype,
        )
        if cache_sharding is not None:
            self.cache = jax.jit(init, out_shardings=cache_sharding)()
        else:
            self.cache = jax.jit(init)()

        def _prefill(params, cache, tokens, table, start, true_len,
                     temp, seed):
            logits, cache = paged_prefill_chunk(
                config, params, cache, tokens, table, start, true_len
            )
            # the fold matches the slot pool's: the chunk's last real
            # position is start + true_len - 1 == prompt_len - 1 on
            # the final chunk — same key, same sampled token
            key = jax.random.fold_in(
                jax.random.key(seed), start + true_len - 1
            )
            return con(sample_token(logits[0], temp, key)), cache

        def _decode(params, cache, tok, pos, temps, seeds, tables):
            logits, cache = paged_decode_step(
                config, params, cache, tok, pos, tables
            )

            def pick_row(lg, temp, seed, p):
                key = jax.random.fold_in(jax.random.key(seed), p)
                return sample_token(lg, temp, key)

            nxt = jax.vmap(pick_row)(logits, temps, seeds, pos)
            return con(nxt), cache

        donate = {}
        if jax.default_backend() != "cpu":
            donate = {"donate_argnums": (1,)}
        self._prefill_c = jax.jit(_prefill, **donate)
        self._decode_c = jax.jit(_decode, **donate)
        self._jnp = jnp

    def prefill_chunk(
        self, tokens: np.ndarray, slot: int, table: np.ndarray,
        start: int, true_len: int, temp: float, seed: int,
    ) -> int:
        """Run one [1, chunk_tokens] prompt chunk at virtual positions
        [start, start + true_len) through ``table``; returns the
        sampled token at the chunk's last real position (meaningful
        only on the prompt's final chunk).  ``slot`` is the engine's
        row id — a protocol rider (the gang driver broadcasts it), the
        math needs only the table."""
        del slot
        first, self.cache = self._prefill_c(
            self.params, self.cache,
            self._put(np.asarray(tokens, np.int32)),
            self._put(np.asarray(table, np.int32)),
            np.int32(start), np.int32(true_len),
            np.float32(temp), np.int32(seed),
        )
        return int(self._jax.device_get(first))

    def decode(
        self, tok: np.ndarray, pos: np.ndarray,
        temps: np.ndarray, seeds: np.ndarray,
        tables: np.ndarray, n_active: Optional[int] = None,
    ) -> np.ndarray:
        """One decode step over the whole pool through per-row page
        tables; ONE bulk device fetch, same as the slot pool."""
        nxt, self.cache = self._decode_c(
            self.params, self.cache,
            self._put(np.asarray(tok, np.int32)),
            self._put(np.asarray(pos, np.int32)),
            self._put(np.asarray(temps, np.float32)),
            self._put(np.asarray(seeds, np.int32)),
            self._put(np.asarray(tables, np.int32)),
        )
        return np.asarray(self._jax.device_get(nxt))

    def export_page(self, page: int) -> dict:
        """Snapshot one physical page as host numpy, every cache key
        included (int8 arenas ship their per-vector scales too — a
        page without its scales decodes to garbage).  Single-caller
        contract like ``prefill_chunk``/``decode``: only the engine
        loop may call this (serve/engine.py routes it through the
        page-I/O queue), since it reads ``self.cache`` mid-stream."""
        return {
            key: np.asarray(self._jax.device_get(arr[:, page]))
            for key, arr in self.cache.items()
        }

    def import_page(self, page: int, payload: dict) -> None:
        """Splice one exported page into physical page ``page`` of
        THIS arena.  Keys must match this pool's cache layout (both
        ends run the same model/kv_dtype — the migration geometry
        check upstream guarantees page_tokens; dtype mismatch raises
        here).  Same single-caller contract as ``export_page``."""
        if set(payload) != set(self.cache):
            raise ValueError(
                f"page payload keys {sorted(payload)} do not match "
                f"arena keys {sorted(self.cache)} (kv_dtype mismatch?)"
            )
        for key, arr in self.cache.items():
            self.cache[key] = arr.at[:, page].set(
                self._jnp.asarray(payload[key], arr.dtype)
            )

    def warm(self) -> None:
        """Compile + execute both entry points before readiness.  All
        tables are zero, so every write lands in the trash page and
        every gather is masked — warmup leaves no residue a real
        request could attend to."""
        self.prefill_chunk(
            np.zeros((1, self.chunk_tokens), np.int32), slot=0,
            table=np.zeros(self.pages_per_row, np.int32),
            start=0, true_len=self.chunk_tokens, temp=0.0, seed=0,
        )
        out = self.decode(
            np.zeros(self.slots, np.int32),
            np.zeros(self.slots, np.int32),
            np.zeros(self.slots, np.float32),
            np.zeros(self.slots, np.int32),
            np.zeros((self.slots, self.pages_per_row), np.int32),
        )
        self._jax.block_until_ready(out)
