"""Deterministic scheduler re-hydration: plan checkpoints + WAL replay.

Reference: the reference's ``SchedulerRestartServiceTest`` pattern —
kill the scheduler anywhere, restart it anywhere, and the plans
resume mid-step because every decision was persisted before it was
acted on.  Most of that already holds here (the launch WAL, the
reservation ledger, ``DeployPlanFactory.seed_step_from_state``, the
startup ``Reconciler``); this module closes the two gaps a failover
harness actually trips:

* **Plan-state checkpoints** — operator verbs (interrupt, proceed,
  force-complete, a started sidecar plan) live only in scheduler
  memory; a restart used to silently resume an interrupted rollout.
  ``PlanCheckpointer`` persists each plan's interrupt flags and
  step statuses as a state-store property whenever they change, and
  ``restore_plans`` replays them into the freshly-rebuilt plan tree —
  never regressing a COMPLETE step (the no-step-regression chaos
  invariant is enforced here by construction).
* **The WAL-replay report** — re-hydration classifies every stored
  launch against agent reality: *adopted* (the task is alive; keep
  it), *re-issued* (WAL'd but the launch never reached an agent — the
  crash landed between WAL and launch; the synthesized LOST status
  sends the step back through evaluation, which relaunches in place
  on the already-committed reservations), *lost* (launched but died
  unobserved; recovery owns it), plus orphan and double-reservation
  scans.  The report is exported at ``GET /v1/debug/ha`` and asserted
  per kill-point by the chaos harness.

Cold start and failover are the same code path: the scheduler runs
this once, inside its first ``run_cycle``, whoever built it and for
whatever reason.
"""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass, field
from typing import Callable, Dict, List, Optional

from dcos_commons_tpu.plan.plan import Plan
from dcos_commons_tpu.plan.status import Status

PLAN_CKPT_PREFIX = "plan-checkpoint-"


@dataclass
class RehydrationReport:
    """What one re-hydration pass found and did."""

    adopted: int = 0            # stored live tasks the agent confirms
    reissued: int = 0           # WAL'd, never launched -> re-driven
    lost: int = 0               # launched, died unobserved -> recovery
    orphans: int = 0            # agent tasks no store owns (swept)
    restored_plans: int = 0     # plans a checkpoint re-shaped
    restored_steps: int = 0     # force-completes/interrupts re-applied
    double_reservations: int = 0  # chip claimed by >1 reservation
    notes: List[str] = field(default_factory=list)

    def to_dict(self) -> dict:
        return asdict(self)


# -- plan-state checkpoints -------------------------------------------


def _raw_status(step) -> Status:
    """The step's stored status, bypassing interrupt/delay overlays
    (an interrupted PENDING step must checkpoint as PENDING+interrupted,
    not WAITING — restore re-applies the overlay separately)."""
    getter = getattr(step, "get_raw_status", None)
    if callable(getter):
        return getter()
    return step.get_status()


def serialize_plan_state(plan: Plan) -> dict:
    return {
        "interrupted": plan.is_interrupted(),
        "phases": {
            phase.name: {
                "interrupted": phase.is_interrupted(),
                "steps": {
                    step.name: {
                        "status": _raw_status(step).value,
                        "interrupted": step.is_interrupted(),
                    }
                    for step in phase.steps
                },
            }
            for phase in plan.phases
        },
    }


class PlanCheckpointer:
    """Persist plan runtime state (interrupts, step statuses) so a
    restarted scheduler resumes at the exact state the operator left.

    One property per plan (namespaced, so multi-service schedulers
    checkpoint independently); writes only on change (the scheduler
    calls this every dirty cycle).  ``chaos`` is the harness's kill
    hook: a crash between the write and the prune — or between two
    plans' writes — must leave a tree ``restore_plans`` tolerates,
    and the chaos tier proves it does.
    """

    def __init__(self, state_store):
        self._state_store = state_store
        self._last: Dict[str, str] = {}
        # plan-name set the last prune ran against: the stale scan is
        # a store enumeration (a remote round trip), and the set only
        # changes at scheduler (re)build — not per dirty cycle
        self._pruned_for: Optional[frozenset] = None

    def checkpoint(
        self,
        plans: Dict[str, Plan],
        chaos: Optional[Callable[[str], None]] = None,
    ) -> int:
        writes = 0
        for name in sorted(plans):
            payload = json.dumps(
                serialize_plan_state(plans[name]), sort_keys=True
            )
            if self._last.get(name) == payload:
                continue
            self._state_store.store_property(
                PLAN_CKPT_PREFIX + name, payload.encode("utf-8")
            )
            self._last[name] = payload
            writes += 1
            if chaos is not None:
                chaos("mid-checkpoint-prune")
        # prune checkpoints of plans that no longer exist (a completed
        # decommission plan, deploy renamed to update across a restart)
        # — only when the plan-name set changed since the last prune:
        # the scan enumerates store keys, which crosses the network on
        # remote state
        names = frozenset(plans)
        if names == self._pruned_for:
            return writes
        for key in self._state_store.fetch_property_keys():
            if not key.startswith(PLAN_CKPT_PREFIX):
                continue
            if key[len(PLAN_CKPT_PREFIX):] in plans:
                continue
            self._state_store.clear_property(key)
            self._last.pop(key[len(PLAN_CKPT_PREFIX):], None)
            writes += 1
            if chaos is not None:
                chaos("mid-checkpoint-prune")
        self._pruned_for = names
        return writes


def restore_plans(
    state_store, plans: Dict[str, Plan], report: RehydrationReport
) -> None:
    """Replay persisted plan checkpoints into freshly-built plans.

    Only the state the task-status replay cannot reconstruct is
    applied: interrupt flags at every level, and force-completed steps
    (checkpoint COMPLETE, rebuilt not complete).  A COMPLETE rebuilt
    step is NEVER regressed, whatever the checkpoint says — the
    checkpoint may predate the statuses that completed it."""
    for name, plan in plans.items():
        raw = state_store.fetch_property(PLAN_CKPT_PREFIX + name)
        if raw is None:
            continue
        try:
            data = json.loads(raw.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            report.notes.append(f"unreadable checkpoint for plan {name}")
            continue
        touched = False
        if bool(data.get("interrupted")) != plan.is_interrupted():
            (plan.interrupt if data.get("interrupted")
             else plan.proceed)()
            touched = True
        for phase in plan.phases:
            ckpt_phase = (data.get("phases") or {}).get(phase.name)
            if ckpt_phase is None:
                continue  # new phase this checkpoint never saw
            if bool(ckpt_phase.get("interrupted")) != phase.is_interrupted():
                (phase.interrupt if ckpt_phase.get("interrupted")
                 else phase.proceed)()
                touched = True
            for step in phase.steps:
                ckpt_step = (ckpt_phase.get("steps") or {}).get(step.name)
                if ckpt_step is None:
                    continue
                if bool(ckpt_step.get("interrupted")) != \
                        step.is_interrupted():
                    (step.interrupt if ckpt_step.get("interrupted")
                     else step.proceed)()
                    report.restored_steps += 1
                    touched = True
                if ckpt_step.get("status") == Status.COMPLETE.value and \
                        not _raw_status(step).is_complete:
                    # a force-complete (or completed work whose statuses
                    # were since cleared) — resume at the exact status
                    step.force_complete()
                    report.restored_steps += 1
                    touched = True
        if touched:
            report.restored_plans += 1


# -- ledger consistency -----------------------------------------------


def scan_double_reservations(ledger, report: RehydrationReport) -> None:
    """A chip claimed by two live reservations is the split-brain
    outcome fencing exists to prevent; re-hydration proves its absence
    on every takeover (and the chaos harness asserts the count is 0)."""
    claimed: Dict[tuple, str] = {}
    for reservation in ledger.all():
        for chip in reservation.chip_ids:
            key = (reservation.host_id, chip)
            prior = claimed.get(key)
            if prior is not None and prior != reservation.reservation_id:
                report.double_reservations += 1
                report.notes.append(
                    f"chip {chip} on {reservation.host_id} claimed by "
                    f"reservations {prior} and {reservation.reservation_id}"
                )
            else:
                claimed[key] = reservation.reservation_id
