"""L4 specification: typed object model of a service + YAML front end.

Reference: sdk/scheduler/.../specification/ (ServiceSpec/PodSpec/
TaskSpec/ResourceSpec interfaces, DefaultServiceSpec.java) and
specification/yaml/ (RawServiceSpec beans, TemplateUtils mustache
rendering, YAMLToInternalMappers.java, 805 LoC).

TPU-first deltas: the resource vocabulary gains a first-class
``tpu:`` block ({generation, chips_per_host, topology}) replacing the
reference's ``gpus:`` Mesos scalar, and pods gain ``gang: true`` for
slice-wide gang scheduling (a pjit mesh cannot roll worker-by-worker;
SURVEY.md section 7 hard part 4).
"""

from dcos_commons_tpu.specification.specs import (
    GoalState,
    HealthCheckSpec,
    PodSpec,
    PortSpec,
    ReadinessCheckSpec,
    ReplacementFailurePolicy,
    ResourceSpec,
    ServiceSpec,
    SpecError,
    TaskSpec,
    TpuSpec,
    UriSpec,
    VolumeSpec,
)
from dcos_commons_tpu.specification.yaml_spec import (
    from_yaml,
    from_yaml_file,
    render_template,
)
from dcos_commons_tpu.specification.validation import (
    ConfigValidationError,
    default_validators,
    validate_spec_change,
)

__all__ = [
    "ConfigValidationError",
    "GoalState",
    "HealthCheckSpec",
    "PodSpec",
    "PortSpec",
    "ReadinessCheckSpec",
    "ReplacementFailurePolicy",
    "ResourceSpec",
    "ServiceSpec",
    "SpecError",
    "TaskSpec",
    "TpuSpec",
    "UriSpec",
    "VolumeSpec",
    "default_validators",
    "from_yaml",
    "from_yaml_file",
    "render_template",
    "validate_spec_change",
]
