"""Offer layer tests: inventory, ledger, placement DSL, torus, evaluator.

Mirrors the reference's offer/evaluate + placement test suites
(OfferEvaluatorTest, PlacementRule tests) over a fabricated fleet.
"""

import pytest

from dcos_commons_tpu.common import TaskInfo
from dcos_commons_tpu.offer import (
    OfferEvaluator,
    Reservation,
    ReservationLedger,
    SliceInventory,
    TpuHost,
    parse_placement,
)
from dcos_commons_tpu.offer.evaluate import ENV_COORDINATOR_ADDRESS
from dcos_commons_tpu.offer.inventory import make_test_fleet
from dcos_commons_tpu.offer.ledger import new_reservation_id
from dcos_commons_tpu.offer.placement import PlacementContext
from dcos_commons_tpu.plan.step import PodInstanceRequirement, RecoveryType
from dcos_commons_tpu.specification import from_yaml
from dcos_commons_tpu.state import StateStore
from dcos_commons_tpu.storage import MemPersister

CPU_YAML = """
name: hello
pods:
  hello:
    count: 2
    placement: 'max-per-host:1'
    tasks:
      server:
        cmd: "serve"
        cpus: 1.0
        memory: 1024
        ports:
          http: {port: 0, vip: "hello:80"}
"""

GANG_YAML = """
name: jax
pods:
  trainer:
    count: 4
    gang: true
    tpu:
      generation: v5e
      chips-per-host: 4
      topology: 4x4
    tasks:
      worker:
        goal: FINISH
        cmd: "python train.py"
        cpus: 2.0
        memory: 4096
"""


def cpu_host(host_id, zone="z1", **kw):
    return TpuHost(host_id=host_id, zone=zone, **kw)


def build_eval(yaml_text, hosts, config="cfg-1"):
    spec = from_yaml(yaml_text)
    persister = MemPersister()
    store = StateStore(persister)
    ledger = ReservationLedger(persister)
    ev = OfferEvaluator(store, ledger, spec.name, config)
    inv = SliceInventory(hosts)
    return spec, store, ledger, ev, inv


# -- inventory / ledger ----------------------------------------------


def test_host_chip_ids():
    fleet = make_test_fleet(host_grid=(2, 2), chip_block=(2, 2))
    h11 = [h for h in fleet if h.grid == (1, 1)][0]
    assert h11.chip_ids() == ["pod-0/2,2", "pod-0/3,2", "pod-0/2,3", "pod-0/3,3"]
    assert h11.chips_per_host == 4


def test_snapshots_subtract_reservations():
    persister = MemPersister()
    ledger = ReservationLedger(persister)
    fleet = make_test_fleet()
    inv = SliceInventory(fleet)
    ledger.commit([
        Reservation(
            reservation_id=new_reservation_id(),
            host_id=fleet[0].host_id,
            task_name="t-0-x",
            cpus=10.0,
            memory_mb=1000,
            chip_ids=fleet[0].chip_ids()[:2],
            ports=[10000],
        )
    ])
    snap = {s.host.host_id: s for s in inv.snapshots(ledger)}[fleet[0].host_id]
    assert snap.cpus == 6.0
    assert len(snap.free_chips) == 2
    assert 10000 in snap.used_ports
    # ledger survives restart
    ledger2 = ReservationLedger(persister)
    assert len(ledger2.all()) == 1
    assert ledger2.unexpected_reservations({"t-0-x"}) == []
    assert len(ledger2.unexpected_reservations({"other"})) == 1


def test_inventory_down_hosts_excluded():
    fleet = make_test_fleet()
    inv = SliceInventory(fleet)
    inv.mark_down(fleet[0].host_id)
    ledger = ReservationLedger(MemPersister())
    assert len(inv.snapshots(ledger)) == 3
    inv.mark_up(fleet[0].host_id)
    assert len(inv.snapshots(ledger)) == 4


# -- placement DSL ----------------------------------------------------


def ctx_with(tasks, hosts):
    return PlacementContext(
        pod_type="hello",
        existing_tasks=tasks,
        hosts={h.host_id: h for h in hosts},
    )


def snap_for(host):
    from dcos_commons_tpu.offer.inventory import ResourceSnapshot
    return ResourceSnapshot(host, host.cpus, host.memory_mb, host.disk_mb,
                            set(host.chip_ids()), set())


def test_max_per_host_rule():
    hosts = [cpu_host("h1"), cpu_host("h2")]
    rule = parse_placement("max-per-host:1")
    existing = [TaskInfo(name="hello-0-server", pod_type="hello",
                         pod_index=0, agent_id="h1")]
    ctx = ctx_with(existing, hosts)
    assert not rule.filter(snap_for(hosts[0]), ctx).passed
    assert rule.filter(snap_for(hosts[1]), ctx).passed


def test_field_and_regex_rules():
    h = cpu_host("h1", zone="us-central2-b")
    ctx = ctx_with([], [h])
    assert parse_placement("zone:exact:us-central2-b").filter(snap_for(h), ctx).passed
    assert not parse_placement("zone:exact:eu-west4-a").filter(snap_for(h), ctx).passed
    assert parse_placement("hostname:regex:h.*").filter(snap_for(h), ctx).passed
    combined = parse_placement("zone:exact:us-central2-b && max-per-host:1")
    assert combined.filter(snap_for(h), ctx).passed


def test_task_type_rules():
    hosts = [cpu_host("h1"), cpu_host("h2")]
    data_task = TaskInfo(name="data-0-node", pod_type="data", pod_index=0,
                         agent_id="h1")
    ctx = ctx_with([data_task], hosts)
    avoid = parse_placement("task-type:avoid:data")
    colocate = parse_placement("task-type:colocate:data")
    assert not avoid.filter(snap_for(hosts[0]), ctx).passed
    assert avoid.filter(snap_for(hosts[1]), ctx).passed
    assert colocate.filter(snap_for(hosts[0]), ctx).passed
    assert not colocate.filter(snap_for(hosts[1]), ctx).passed


def test_marathon_dialect():
    hosts = [cpu_host("h1", zone="a"), cpu_host("h2", zone="b")]
    existing = [TaskInfo(name="hello-0-server", pod_type="hello", pod_index=0,
                         agent_id="h1")]
    ctx = ctx_with(existing, hosts)
    unique = parse_placement('[["hostname", "UNIQUE"]]')
    assert not unique.filter(snap_for(hosts[0]), ctx).passed
    assert unique.filter(snap_for(hosts[1]), ctx).passed
    like = parse_placement('[["zone", "LIKE", "a"]]')
    assert like.filter(snap_for(hosts[0]), ctx).passed
    assert not like.filter(snap_for(hosts[1]), ctx).passed
    unlike = parse_placement('[["zone", "UNLIKE", "a"]]')
    assert not unlike.filter(snap_for(hosts[0]), ctx).passed
    with pytest.raises(ValueError):
        parse_placement('[["zone", "TELEPORT"]]')
    with pytest.raises(ValueError):
        parse_placement("teleport:3")


# -- evaluator: CPU pods ----------------------------------------------


def test_evaluate_cpu_pod_with_ports():
    spec, store, ledger, ev, inv = build_eval(
        CPU_YAML, [cpu_host("h1"), cpu_host("h2")]
    )
    req = PodInstanceRequirement(pod=spec.pod("hello"), instances=[0])
    result = ev.evaluate(req, inv)
    assert result.passed, result.outcome.flatten()
    assert len(result.task_infos) == 1
    info = result.task_infos[0]
    assert info.name == "hello-0-server"
    assert "PORT_HTTP" in info.env
    assert info.labels["target_configuration"] == "cfg-1"
    # commit + store, then the second instance must avoid h1
    ledger.commit(result.reservations)
    store.store_tasks(result.task_infos)
    req2 = PodInstanceRequirement(pod=spec.pod("hello"), instances=[1])
    result2 = ev.evaluate(req2, inv)
    assert result2.passed
    assert result2.task_infos[0].agent_id != info.agent_id


def test_evaluate_fails_when_full():
    spec, store, ledger, ev, inv = build_eval(CPU_YAML, [cpu_host("h1")])
    req0 = PodInstanceRequirement(pod=spec.pod("hello"), instances=[0])
    r0 = ev.evaluate(req0, inv)
    ledger.commit(r0.reservations)
    store.store_tasks(r0.task_infos)
    r1 = ev.evaluate(
        PodInstanceRequirement(pod=spec.pod("hello"), instances=[1]), inv
    )
    assert not r1.passed
    # the "why" is explainable (outcome tracker contract)
    text = "\n".join(r1.outcome.flatten())
    assert "max-per-host" in text


def test_evaluate_reuse_in_place():
    """TRANSIENT relaunch reuses the committed footprint."""
    spec, store, ledger, ev, inv = build_eval(
        CPU_YAML, [cpu_host("h1"), cpu_host("h2")]
    )
    req = PodInstanceRequirement(pod=spec.pod("hello"), instances=[0])
    first = ev.evaluate(req, inv)
    ledger.commit(first.reservations)
    store.store_tasks(first.task_infos)
    again = ev.evaluate(
        PodInstanceRequirement(
            pod=spec.pod("hello"), instances=[0],
            recovery_type=RecoveryType.TRANSIENT,
        ),
        inv,
    )
    assert again.passed
    assert again.reservations == []  # no new claims
    assert again.task_infos[0].agent_id == first.task_infos[0].agent_id
    assert again.task_infos[0].env.get("PORT_HTTP") == \
        first.task_infos[0].env.get("PORT_HTTP")
    # PERMANENT forces fresh placement with NEW reservations (the old
    # footprint is later reclaimed as unexpected-resource GC, mirroring
    # DefaultScheduler.java:483-538); same-host is allowed if placement
    # rules pass and the host is up
    replaced = ev.evaluate(
        PodInstanceRequirement(
            pod=spec.pod("hello"), instances=[0],
            recovery_type=RecoveryType.PERMANENT,
        ),
        inv,
    )
    assert replaced.passed
    assert replaced.reservations  # new claims
    old_ids = {r.reservation_id for r in first.reservations}
    new_ids = {r.reservation_id for r in replaced.reservations}
    assert not (old_ids & new_ids)


def test_evaluate_reuse_skipped_when_host_down():
    spec, store, ledger, ev, inv = build_eval(
        CPU_YAML, [cpu_host("h1"), cpu_host("h2")]
    )
    req = PodInstanceRequirement(pod=spec.pod("hello"), instances=[0])
    first = ev.evaluate(req, inv)
    ledger.commit(first.reservations)
    store.store_tasks(first.task_infos)
    inv.mark_down(first.task_infos[0].agent_id)
    relaunch = ev.evaluate(
        PodInstanceRequirement(
            pod=spec.pod("hello"), instances=[0],
            recovery_type=RecoveryType.TRANSIENT,
        ),
        inv,
    )
    assert relaunch.passed
    assert relaunch.task_infos[0].agent_id != first.task_infos[0].agent_id


# -- evaluator: gang TPU pods ----------------------------------------


def test_evaluate_gang_torus():
    fleet = make_test_fleet(host_grid=(4, 4), chip_block=(2, 2))
    spec, store, ledger, ev, inv = build_eval(GANG_YAML, fleet)
    req = PodInstanceRequirement(
        pod=spec.pod("trainer"), instances=[0, 1, 2, 3]
    )
    result = ev.evaluate(req, inv)
    assert result.passed, result.outcome.flatten()
    assert len(result.task_infos) == 4
    # hosts form a contiguous 2x2 host rectangle (4x4 chips of 2x2 blocks)
    grids = sorted(
        inv.host(i.agent_id).grid for i in result.task_infos
    )
    assert grids == [(0, 0), (0, 1), (1, 0), (1, 1)]
    # all workers share one coordinator address pointing at worker 0
    coords = {i.env[ENV_COORDINATOR_ADDRESS] for i in result.task_infos}
    assert len(coords) == 1
    worker0 = [i for i in result.task_infos if i.env["TPU_WORKER_ID"] == "0"][0]
    assert coords.pop().startswith(worker0.agent_id)
    assert worker0.env["TPU_TOPOLOGY"] == "4x4"
    assert len(worker0.tpu_chip_ids) == 4


def test_gang_torus_avoids_reserved_hosts():
    fleet = make_test_fleet(host_grid=(4, 2), chip_block=(2, 2))
    spec, store, ledger, ev, inv = build_eval(GANG_YAML, fleet)
    # burn a chip on host (0,0): the 2x2 anchor must shift right
    blocked = [h for h in fleet if h.grid == (0, 0)][0]
    ledger.commit([
        Reservation(
            reservation_id=new_reservation_id(), host_id=blocked.host_id,
            task_name="intruder-0-x", chip_ids=blocked.chip_ids()[:1],
        )
    ])
    req = PodInstanceRequirement(
        pod=spec.pod("trainer"), instances=[0, 1, 2, 3]
    )
    result = ev.evaluate(req, inv)
    assert result.passed
    grids = sorted(inv.host(i.agent_id).grid for i in result.task_infos)
    assert grids == [(1, 0), (1, 1), (2, 0), (2, 1)] or \
        grids == [(2, 0), (2, 1), (3, 0), (3, 1)]


MULTISLICE_YAML = """
name: jax
pods:
  trainer:
    count: 4
    gang: true
    tpu:
      generation: v5e
      chips-per-host: 4
      topology: 2x4
      slices: 2
    tasks:
      worker:
        goal: FINISH
        cmd: "python train.py"
        cpus: 2.0
        memory: 4096
"""


def test_evaluate_multislice_gang():
    """tpu: slices: 2 — two slice-local 2x4 sub-gangs in DISTINCT
    slices, slice-major worker ids, one global coordinator, and the
    TPU_SLICE_INDEX/TPU_NUM_SLICES contract for the dcn mesh axis."""
    fleet = (
        make_test_fleet(slice_id="pod-a", host_grid=(1, 2),
                        chip_block=(2, 2))
        + make_test_fleet(slice_id="pod-b", host_grid=(1, 2),
                          chip_block=(2, 2))
        + make_test_fleet(slice_id="pod-c", host_grid=(1, 2),
                          chip_block=(2, 2))
    )
    spec, store, ledger, ev, inv = build_eval(MULTISLICE_YAML, fleet)
    req = PodInstanceRequirement(
        pod=spec.pod("trainer"), instances=[0, 1, 2, 3]
    )
    result = ev.evaluate(req, inv)
    assert result.passed, result.outcome.flatten()
    assert len(result.task_infos) == 4
    by_worker = sorted(
        result.task_infos, key=lambda i: int(i.env["TPU_WORKER_ID"])
    )
    # slice-major numbering: workers 0-1 in one slice, 2-3 in another
    slice_of = [inv.host(i.agent_id).slice_id for i in by_worker]
    assert slice_of[0] == slice_of[1]
    assert slice_of[2] == slice_of[3]
    assert slice_of[0] != slice_of[2]
    assert [i.env["TPU_SLICE_INDEX"] for i in by_worker] == \
        ["0", "0", "1", "1"]
    assert all(i.env["TPU_NUM_SLICES"] == "2" for i in by_worker)
    # ONE coordinator for the whole multi-slice gang, on worker 0
    coords = {i.env[ENV_COORDINATOR_ADDRESS] for i in result.task_infos}
    assert len(coords) == 1
    assert coords.pop().startswith(by_worker[0].agent_id)


def test_multislice_gang_relaunch_restores_slice_env():
    """A TRANSIENT in-place relaunch of a slices>1 gang must carry the
    same TPU_SLICE_INDEX/TPU_NUM_SLICES contract the claim path set —
    losing it builds a dcn-less mesh (r3 advisor, evaluate.py reuse)."""
    fleet = (
        make_test_fleet(slice_id="pod-a", host_grid=(1, 2),
                        chip_block=(2, 2))
        + make_test_fleet(slice_id="pod-b", host_grid=(1, 2),
                          chip_block=(2, 2))
    )
    spec, store, ledger, ev, inv = build_eval(MULTISLICE_YAML, fleet)
    req = PodInstanceRequirement(
        pod=spec.pod("trainer"), instances=[0, 1, 2, 3]
    )
    first = ev.evaluate(req, inv)
    assert first.passed, first.outcome.flatten()
    ledger.commit(first.reservations)
    store.store_tasks(first.task_infos)
    again = ev.evaluate(
        PodInstanceRequirement(
            pod=spec.pod("trainer"), instances=[0, 1, 2, 3],
            recovery_type=RecoveryType.TRANSIENT,
        ),
        inv,
    )
    assert again.passed, again.outcome.flatten()
    assert again.reservations == []  # in-place: no new claims
    by_worker = sorted(
        again.task_infos, key=lambda i: int(i.env["TPU_WORKER_ID"])
    )
    assert [i.env.get("TPU_SLICE_INDEX") for i in by_worker] == \
        ["0", "0", "1", "1"]
    assert all(i.env.get("TPU_NUM_SLICES") == "2" for i in by_worker)


def test_multislice_gang_needs_distinct_slices():
    """One free slice cannot host a slices: 2 gang — and the outcome
    says which sub-gang failed."""
    fleet = make_test_fleet(slice_id="pod-a", host_grid=(1, 2),
                            chip_block=(2, 2))
    spec, store, ledger, ev, inv = build_eval(MULTISLICE_YAML, fleet)
    req = PodInstanceRequirement(
        pod=spec.pod("trainer"), instances=[0, 1, 2, 3]
    )
    result = ev.evaluate(req, inv)
    assert not result.passed
    assert "no free slice for sub-gang 2/2" in result.outcome.reason


def test_gang_torus_no_capacity_explains():
    fleet = make_test_fleet(host_grid=(1, 1), chip_block=(2, 2))
    spec, store, ledger, ev, inv = build_eval(GANG_YAML, fleet)
    req = PodInstanceRequirement(
        pod=spec.pod("trainer"), instances=[0, 1, 2, 3]
    )
    result = ev.evaluate(req, inv)
    assert not result.passed
    text = "\n".join(result.outcome.flatten())
    assert "smaller than required" in text


def test_gang_atomicity_no_partial_claims():
    """A gang that cannot fully place claims NOTHING."""
    fleet = make_test_fleet(host_grid=(2, 2), chip_block=(2, 2), cpus=1.0)
    # trainer needs 2 cpus/host but hosts have 1: must fail with zero
    # reservations
    spec, store, ledger, ev, inv = build_eval(GANG_YAML, fleet)
    result = ev.evaluate(
        PodInstanceRequirement(pod=spec.pod("trainer"), instances=[0, 1, 2, 3]),
        inv,
    )
    assert not result.passed
    assert result.reservations == []
    assert ledger.all() == []


def test_gang_relaunch_without_coordinator_fails_loudly():
    """Regression: a gang relaunch whose coordinator reservation is
    gone must FAIL evaluation, not launch workers with an empty
    COORDINATOR_ADDRESS that hang in jax.distributed.initialize."""
    from dcos_commons_tpu.offer.evaluate import COORDINATOR_PORT_NAME

    fleet = make_test_fleet(host_grid=(4, 4), chip_block=(2, 2))
    spec, store, ledger, ev, inv = build_eval(GANG_YAML, fleet)
    req = PodInstanceRequirement(
        pod=spec.pod("trainer"), instances=[0, 1, 2, 3]
    )
    first = ev.evaluate(req, inv)
    assert first.passed
    ledger.commit(first.reservations)
    store.store_tasks(first.task_infos)

    # simulate partial state loss: only the rendezvous claim vanishes
    for r in ledger.all():
        if r.container_path == COORDINATOR_PORT_NAME:
            ledger.release(r.reservation_id)

    relaunch = ev.evaluate(req, inv)
    assert not relaunch.passed
    assert "coordinator" in "\n".join(relaunch.outcome.flatten())
    assert relaunch.task_infos == []


def test_agent_rule_match_and_drain():
    """agent:exact pins to host ids; agent:avoid is the maintenance
    drain verb (reference: AgentRule)."""
    hosts = [cpu_host("h1"), cpu_host("h2"), cpu_host("h3")]
    ctx = ctx_with([], hosts)
    pin = parse_placement("agent:exact:h1,h2")
    assert pin.filter(snap_for(hosts[0]), ctx).passed
    assert not pin.filter(snap_for(hosts[2]), ctx).passed
    drain = parse_placement("agent:avoid:h3")
    assert drain.filter(snap_for(hosts[0]), ctx).passed
    outcome = drain.filter(snap_for(hosts[2]), ctx)
    assert not outcome.passed
    assert "drained" in outcome.reason


def test_round_robin_rule_balances_zones():
    """round-robin:zone never lets one zone get 2 ahead of the
    emptiest (reference: RoundRobinByZoneRule)."""
    hosts = [
        cpu_host("a1", zone="za"), cpu_host("a2", zone="za"),
        cpu_host("b1", zone="zb"),
    ]
    rule = parse_placement("round-robin:zone")
    one_in_za = [TaskInfo(name="hello-0-server", pod_type="hello",
                          pod_index=0, agent_id="a1")]
    ctx = ctx_with(one_in_za, hosts)
    # za is at 1, zb at 0: only zb placements pass
    assert not rule.filter(snap_for(hosts[1]), ctx).passed
    assert rule.filter(snap_for(hosts[2]), ctx).passed
    # balanced again: both pass
    balanced = one_in_za + [TaskInfo(name="hello-1-server", pod_type="hello",
                                     pod_index=1, agent_id="b1")]
    ctx = ctx_with(balanced, hosts)
    assert rule.filter(snap_for(hosts[1]), ctx).passed
    assert rule.filter(snap_for(hosts[2]), ctx).passed


def test_placement_disjunction():
    hosts = [cpu_host("h1", zone="za"), cpu_host("h2", zone="zb"),
             cpu_host("h3", zone="zc")]
    ctx = ctx_with([], hosts)
    rule = parse_placement("zone:exact:za || zone:exact:zb && hostname:regex:h.*")
    assert rule.filter(snap_for(hosts[0]), ctx).passed
    assert rule.filter(snap_for(hosts[1]), ctx).passed
    assert not rule.filter(snap_for(hosts[2]), ctx).passed


def test_bad_placement_is_config_error():
    from dcos_commons_tpu.specification.validation import (
        ConfigValidationError,
        validate_spec_change,
    )

    spec = from_yaml("""
name: bad-placement
pods:
  app:
    count: 1
    placement: 'no-such-rule:1'
    tasks:
      main: {goal: RUNNING, cmd: "x", cpus: 0.1, memory: 32}
""")
    import pytest as _pytest

    with _pytest.raises(ConfigValidationError) as err:
        validate_spec_change(None, spec)
    assert "placement" in str(err.value)


# -- torus wrap-around + odd shapes ----------------------------------


def _row_fleet(n, wrap=""):
    """n hosts in a 1-row slice, 2x2 chips each."""
    hosts = []
    for i in range(n):
        hosts.append(TpuHost(
            host_id=f"r{i}",
            slice_id="row-slice",
            generation="v5e",
            grid=(i, 0),
            chip_block=(2, 2),
            cpus=8.0,
            memory_mb=16384,
            attributes=(
                {"ici_wrap": wrap, "ring_x": str(n), "ring_y": "1"}
                if wrap else {}
            ),
        ))
    return hosts


def _all_ok(snap):
    from dcos_commons_tpu.offer.outcome import EvaluationOutcome
    return EvaluationOutcome.ok("test")


def test_torus_no_wrap_blocked_by_middle_host():
    from dcos_commons_tpu.offer.torus import find_subslice

    inv = SliceInventory(_row_fleet(3))
    ledger = ReservationLedger(MemPersister())
    # reserve the middle host's chips: no contiguous 2-host rect left
    middle = _row_fleet(3)[1]
    ledger.commit([Reservation(
        reservation_id=new_reservation_id(), host_id="r1",
        task_name="blocker-0-x", chip_ids=middle.chip_ids(),
    )])
    placement = find_subslice(
        inv.snapshots(ledger), (4, 2), 4, _all_ok
    )
    assert placement.snapshots == []


def test_torus_wrap_spans_the_edge():
    from dcos_commons_tpu.offer.torus import find_subslice

    fleet = _row_fleet(3, wrap="x")
    inv = SliceInventory(fleet)
    ledger = ReservationLedger(MemPersister())
    ledger.commit([Reservation(
        reservation_id=new_reservation_id(), host_id="r1",
        task_name="blocker-0-x", chip_ids=fleet[1].chip_ids(),
    )])
    placement = find_subslice(
        inv.snapshots(ledger), (4, 2), 4, _all_ok
    )
    # r2 + r0 across the wrap link form the 4x2 rectangle
    assert [s.host.host_id for s in placement.snapshots] == ["r2", "r0"]


def test_torus_odd_shape_not_tileable():
    from dcos_commons_tpu.offer.torus import find_subslice

    inv = SliceInventory(_row_fleet(3))
    ledger = ReservationLedger(MemPersister())
    placement = find_subslice(inv.snapshots(ledger), (3, 2), 4, _all_ok)
    assert placement.snapshots == []
    assert any(
        "not tileable" in c.reason for c in placement.outcome.children
    )


def test_torus_full_ring_uses_every_host():
    from dcos_commons_tpu.offer.torus import find_subslice

    inv = SliceInventory(_row_fleet(4, wrap="x"))
    ledger = ReservationLedger(MemPersister())
    placement = find_subslice(inv.snapshots(ledger), (8, 2), 4, _all_ok)
    assert len(placement.snapshots) == 4


def test_torus_wrap_needs_physical_ring_size():
    """Wrap modulo must come from the declared hardware ring, never
    the observed extent of up hosts: with the edge host DOWN, the
    shrunken extent must not join non-adjacent hosts."""
    from dcos_commons_tpu.offer.torus import find_subslice

    fleet = _row_fleet(4, wrap="x")  # ring_x=4
    inv = SliceInventory(fleet)
    inv.mark_down("r3")  # the physical wrap neighbor of r0
    ledger = ReservationLedger(MemPersister())
    ledger.commit([Reservation(
        reservation_id=new_reservation_id(), host_id="r1",
        task_name="blocker-0-x", chip_ids=fleet[1].chip_ids(),
    )])
    placement = find_subslice(inv.snapshots(ledger), (4, 2), 4, _all_ok)
    # r2+r0 would need the link through the down host r3: refuse
    assert placement.snapshots == []


def test_round_robin_partial_topology_knowledge():
    """round-robin:zone:3 with only 2 zones visible: the declared but
    unseen zone is empty by definition, so non-empty zones fail."""
    hosts = [cpu_host("a1", zone="za"), cpu_host("b1", zone="zb")]
    rule = parse_placement("round-robin:zone:3")
    ctx = ctx_with(
        [TaskInfo(name="hello-0-server", pod_type="hello", pod_index=0,
                  agent_id="a1"),
         TaskInfo(name="hello-1-server", pod_type="hello", pod_index=1,
                  agent_id="b1")],
        hosts,
    )
    assert not rule.filter(snap_for(hosts[0]), ctx).passed
    assert not rule.filter(snap_for(hosts[1]), ctx).passed


def test_malformed_placement_arity_is_config_error():
    from dcos_commons_tpu.specification.validation import (
        ConfigValidationError,
        validate_spec_change,
    )

    for bad in ("group-by", "max-per-host", "agent:exact"):
        spec = from_yaml(f"""
name: bad-arity
pods:
  app:
    count: 1
    placement: '{bad}'
    tasks:
      main: {{goal: RUNNING, cmd: "x", cpus: 0.1, memory: 32}}
""")
        import pytest as _pytest

        with _pytest.raises(ConfigValidationError):
            validate_spec_change(None, spec)


def test_gang_tasks_carry_libtpu_provisioning_env():
    """Each gang worker's env carries ITS host's chip ids and the
    host chip-grid bounds (the libtpu provisioning contract the
    reference's bootstrap provided task-side)."""
    fleet = make_test_fleet(host_grid=(2, 2), chip_block=(2, 2))
    spec, store, ledger, ev, inv = build_eval(GANG_YAML, fleet)
    from dcos_commons_tpu.plan.step import PodInstanceRequirement

    req = PodInstanceRequirement(
        pod=spec.pod("trainer"), instances=[0, 1, 2, 3]
    )
    result = ev.evaluate(req, inv)
    assert result.passed
    seen_chips = []
    for info in result.task_infos:
        env = info.env
        assert env["TPU_CHIPS_PER_HOST_BOUNDS"] == "2,2,1"
        chip_ids = env["TPU_CHIP_IDS"].split(";")
        assert len(chip_ids) == 4  # this HOST's chips only
        seen_chips.append(frozenset(chip_ids))
    # no two workers share chips
    assert len(set(seen_chips)) == len(seen_chips)


def test_partial_and_sidecar_tasks_get_no_bounds_contract():
    """A partial-host chip allocation emits chip ids but NO grid
    bounds (no rectangular contract to claim), and a chip-less sidecar
    gets neither var — the libtpu provisioning env is all-or-nothing."""
    fleet = make_test_fleet(host_grid=(1, 1), chip_block=(2, 2))
    partial_yaml = """
name: partial
pods:
  worker:
    count: 1
    tpu:
      generation: v5e
      chips-per-host: 2
    tasks:
      main: {goal: RUNNING, cmd: "x", cpus: 0.5, memory: 64}
      side: {goal: ONCE, cmd: "y", cpus: 0.1, memory: 32}
"""
    spec, store, ledger, ev, inv = build_eval(partial_yaml, fleet)
    from dcos_commons_tpu.plan.step import PodInstanceRequirement

    result = ev.evaluate(
        PodInstanceRequirement(
            pod=spec.pod("worker"), instances=[0],
            tasks_to_launch=["main"],
        ),
        inv,
    )
    assert result.passed
    env = result.task_infos[0].env
    assert len(env["TPU_CHIP_IDS"].split(";")) == 2
    assert "TPU_CHIPS_PER_HOST_BOUNDS" not in env
    ledger.commit(result.reservations)
    store.store_tasks(result.task_infos)
    # sidecar colocates with zero chips: neither provisioning var
    side = ev.evaluate(
        PodInstanceRequirement(
            pod=spec.pod("worker"), instances=[0],
            tasks_to_launch=["side"],
        ),
        inv,
    )
    assert side.passed
    side_env = side.task_infos[0].env
    assert "TPU_CHIP_IDS" not in side_env
    assert "TPU_CHIPS_PER_HOST_BOUNDS" not in side_env


def test_colaunched_sidecar_in_one_requirement_gets_no_chip_env():
    """Both tasks of a TPU pod launched in ONE requirement: only the
    reservation-holding task carries the chip provisioning env."""
    fleet = make_test_fleet(host_grid=(1, 1), chip_block=(2, 2))
    yaml_text = """
name: both
pods:
  worker:
    count: 1
    tpu:
      generation: v5e
      chips-per-host: 4
    tasks:
      main: {goal: RUNNING, cmd: "x", cpus: 0.5, memory: 64}
      side: {goal: ONCE, cmd: "y", cpus: 0.1, memory: 32}
"""
    spec, store, ledger, ev, inv = build_eval(yaml_text, fleet)
    from dcos_commons_tpu.plan.step import PodInstanceRequirement

    result = ev.evaluate(
        PodInstanceRequirement(pod=spec.pod("worker"), instances=[0]),
        inv,
    )
    assert result.passed
    envs = {i.name: i.env for i in result.task_infos}
    with_chips = [n for n, e in envs.items() if "TPU_CHIP_IDS" in e]
    assert len(with_chips) == 1, envs
    # bounds travel WITH the chips, never alone
    for name, env in envs.items():
        assert ("TPU_CHIPS_PER_HOST_BOUNDS" in env) == (name in with_chips)
