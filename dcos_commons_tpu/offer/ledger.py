"""Reservation ledger: the WAL-backed resource claim book.

Replaces Mesos reservations + resource-id labels (reference:
offer/ResourceBuilder.java resource-id stamping, offer/ResourceUtils,
and the RESERVE/UNRESERVE operations sent via OfferAccepter).  Without
a Mesos master to arbitrate, the ledger IS the arbiter: a resource is
ours iff a reservation is committed here, and reservations are written
*before* launch (the PersistentLaunchRecorder discipline,
SURVEY.md section 7 hard part 1).

GC: reservations whose task no longer exists surface through
``unexpected_reservations`` — the analogue of the reference's
unexpected-resource cleanup (DefaultScheduler.java:483-538).
"""

from __future__ import annotations

import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from dcos_commons_tpu.common import SerializableMixin
from dcos_commons_tpu.offer.inventory import ReservationLedgerView
from dcos_commons_tpu.storage import Persister, SetOp
from dcos_commons_tpu.storage.persister import namespace_root, validate_key


@dataclass
class Reservation(SerializableMixin):
    reservation_id: str
    host_id: str
    task_name: str = ""              # "<pod>-<i>-<task>" owning this claim
    role: str = ""
    cpus: float = 0.0
    memory_mb: int = 0
    disk_mb: int = 0
    chip_ids: List[str] = field(default_factory=list)
    ports: List[int] = field(default_factory=list)
    volume_id: str = ""              # persistent volume surviving relaunch
    container_path: str = ""
    # container_path -> durable volume key for EVERY volume of the
    # task; sibling tasks of one pod instance that declare the same
    # container path share the key (one durable dir per instance+path)
    volumes: Dict[str, str] = field(default_factory=dict)


def new_reservation_id() -> str:
    return uuid.uuid4().hex


class ReservationLedger(ReservationLedgerView):
    """Persisted under /reservations/<id>; cached in RAM for scans.

    The RAM cache carries two scan accelerators for the offer cycle's
    hot path: by-host and by-task indexes (``reserved_on``/``for_task``
    are O(claims on that host/task) instead of O(all claims)), and a
    monotonic generation counter bumped on every commit/release.  Each
    host records the generation of its last mutation, so
    ``SliceInventory.snapshots`` can reuse a cached per-host snapshot
    whenever ``host_generation`` is unchanged.
    """

    def __init__(self, persister: Persister, namespace: str = "") -> None:
        self._persister = persister
        self._root = namespace_root(namespace)
        self._cache: Dict[str, Reservation] = {}
        self._by_host: Dict[str, Dict[str, Reservation]] = {}
        self._by_task: Dict[str, Dict[str, Reservation]] = {}
        self._generation = 1
        # generation counters restart at 1 for every ledger OBJECT (a
        # service upgrade/reinstall rebuilds the ledger over the same
        # persisted tree): the epoch disambiguates, so a change token
        # minted against the old object can never alias the new one's
        # rebased generations (a stale-but-colliding token would hide
        # the final pre-rebuild commits from snapshot caches forever)
        self._epoch = uuid.uuid4().hex[:12]
        self._host_gen: Dict[str, int] = {}
        # newest pruned stamp: tokens older than this can no longer be
        # answered incrementally (the pruned host's change would be
        # invisible to them) and fall back to a full resync
        self._prune_floor = 0
        self._load()

    def _path(self, reservation_id: str) -> str:
        validate_key(reservation_id, "reservation id")
        return f"{self._root}/reservations/{reservation_id}"

    def _load(self) -> None:
        for rid in self._persister.get_children_or_empty(
            f"{self._root}/reservations"
        ):
            raw = self._persister.get_or_none(self._path(rid))
            if raw is not None:
                self._index(Reservation.from_bytes(raw))

    def _index(self, r: Reservation) -> None:
        old = self._cache.get(r.reservation_id)
        if old is not None:
            self._unindex(old)
        self._cache[r.reservation_id] = r
        self._by_host.setdefault(r.host_id, {})[r.reservation_id] = r
        self._by_task.setdefault(r.task_name, {})[r.reservation_id] = r
        self._host_gen[r.host_id] = self._generation

    def _unindex(self, r: Reservation) -> None:
        self._cache.pop(r.reservation_id, None)
        for index, key in ((self._by_host, r.host_id),
                           (self._by_task, r.task_name)):
            bucket = index.get(key)
            if bucket is not None:
                bucket.pop(r.reservation_id, None)
                if not bucket:
                    del index[key]
        self._host_gen[r.host_id] = self._generation

    # -- commit / release --------------------------------------------

    def commit(self, reservations: List[Reservation]) -> None:
        """Atomically commit a group of reservations (gang = one txn)."""
        ops = [
            SetOp(self._path(r.reservation_id), r.to_bytes())
            for r in reservations
        ]
        # durcheck: dur-unfenced-write=the scheduler builder hands this ledger a FencedPersister in HA mode; the fence is in the injected instance
        self._persister.apply(ops)
        self._generation += 1
        for r in reservations:
            self._index(r)

    def release(self, reservation_id: str) -> None:
        from dcos_commons_tpu.storage import PersisterError

        path = self._path(reservation_id)
        try:
            # durcheck: dur-unfenced-write=same injected FencedPersister as commit(); a deposed leader's delete raises through the fence
            self._persister.recursive_delete(path)
        except PersisterError:
            pass
        old = self._cache.get(reservation_id)
        if old is not None:
            self._generation += 1
            self._unindex(old)
            self._compact_host_gen()

    def _compact_host_gen(self) -> None:
        """Prune generation stamps of hosts with no live claims once
        the journal exceeds 2x the claimed-host set — months of fleet
        churn (every replaced host once held a reservation) must not
        grow memory or per-sync dirty-scan cost without bound.  The
        same discipline as SliceInventory's topology-journal
        compaction: anything pruned raises ``_prune_floor`` so a
        pre-compaction token resyncs from scratch instead of missing
        the pruned host's release."""
        if len(self._host_gen) <= max(16, 2 * len(self._by_host)):
            return
        for host_id in [
            h for h in self._host_gen if h not in self._by_host
        ]:
            stamp = self._host_gen.pop(host_id)
            if stamp > self._prune_floor:
                self._prune_floor = stamp

    # -- queries ------------------------------------------------------

    @property
    def generation(self) -> int:
        """Monotonic mutation counter (bumped per commit/release)."""
        return self._generation

    def host_generation(self, host_id: str) -> int:
        """Generation of the last mutation touching ``host_id`` (0 =
        never touched).  Snapshot caches key on this value."""
        return self._host_gen.get(host_id, 0)

    @property
    def epoch(self) -> str:
        """Identity of this ledger OBJECT; tokens carry it so a
        rebuilt ledger's rebased generations never alias stale ones."""
        return self._epoch

    def generation_token(self):
        """Whole-ledger change token for incremental snapshot sync
        (SliceInventory dirty-host evaluation)."""
        return (self._epoch, self._generation)

    def changed_hosts_since(self, token) -> Optional[Set[str]]:
        """Hosts whose claims changed after ``token`` — the dirty set
        an incremental snapshot sync rebuilds.  O(1) when nothing
        changed; otherwise O(stamp journal), which compaction bounds
        at 2x the currently-claimed host set.  A token from another
        epoch (a superseded ledger object), from the future, or
        predating a compaction returns None: the caller must treat
        every host as dirty."""
        if not (
            isinstance(token, tuple)
            and len(token) == 2
            and token[0] == self._epoch
            and isinstance(token[1], int)
        ):
            return None
        if token[1] > self._generation:
            return None
        if token[1] == self._generation:
            return set()
        if token[1] < self._prune_floor:
            return None  # a pruned stamp postdates this token
        return {h for h, g in self._host_gen.items() if g > token[1]}

    def get(self, reservation_id: str) -> Optional[Reservation]:
        return self._cache.get(reservation_id)

    def all(self) -> List[Reservation]:
        return list(self._cache.values())

    def reserved_on(self, host_id: str) -> List[Reservation]:
        return list(self._by_host.get(host_id, {}).values())

    def for_task(self, task_name: str) -> List[Reservation]:
        return list(self._by_task.get(task_name, {}).values())

    def unexpected_reservations(self, expected_task_names: Set[str]) -> List[Reservation]:
        """Claims owned by no live task — candidates for UNRESERVE GC
        (reference: MesosEventClient.getUnexpectedResources)."""
        return [
            r
            for r in self._cache.values()
            if r.task_name not in expected_task_names
        ]
