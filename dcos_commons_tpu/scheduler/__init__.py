"""L2 core: the service scheduler and its builder.

Reference: sdk/scheduler/.../scheduler/ — MesosEventClient.java:14-68
(the event contract), AbstractScheduler.java (reconcile gate, work-set
revive), DefaultScheduler.java:81 (offer->plan wiring :423-470,
unexpected-resource GC :483-538, status fan-out :541-568),
SchedulerBuilder.java:331 (persister/state/config wiring, deploy-vs-
update plan selection :644), SchedulerRunner.java:82.
"""

from dcos_commons_tpu.scheduler.config import SchedulerConfig
from dcos_commons_tpu.scheduler.scheduler import DefaultScheduler
from dcos_commons_tpu.scheduler.builder import SchedulerBuilder

__all__ = ["DefaultScheduler", "SchedulerBuilder", "SchedulerConfig"]
